"""Distribution-level sampler observability (``obs/sampler_health.py``
plus the step's ``sampler_dist/*`` emitters): the in-graph log-binned
histograms are pinned bit-exact to their numpy reference, the
selection-count ledger is pinned EXACT against host-counted draws (body
path by replaying the draw chain from the pre-step state, host_stream by
reading the pending-selection ring front), the grad-variance probe is
cross-validated against ``benchmarks/grad_variance.py``'s convention
(``ratio < 1`` ⇔ importance sampling wins), and the ledger survives
checkpoint/restore and elastic W→W′ resharding with exact per-sample
counts."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.obs.sampler_health import (
    HIST_BINS,
    SCORE_HIST_HI,
    SCORE_HIST_LO,
    WEIGHT_HIST_HI,
    WEIGHT_HIST_LO,
    SamplerHealthMonitor,
    bias_audit,
    class_spread,
    gini,
    hist_bin_edges,
    hist_keys,
    ledger_global_counts,
    log_bin_histogram,
    log_bin_histogram_np,
    sparkline,
    table_probs_np,
    variance_probe_ratio,
)
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def mesh():
    return host_cpu_mesh(4)


@pytest.fixture(scope="module")
def mesh1():
    return host_cpu_mesh(1)


def table_cfg(**kw) -> TrainConfig:
    base = dict(
        model="smallcnn",
        dataset="synthetic",
        world_size=4,
        batch_size=8,
        presample_batches=2,
        num_epochs=1,
        steps_per_epoch=200,
        eval_every=0,
        log_every=0,
        heartbeat_every=0,
        checkpoint_every=0,
        compute_dtype="float32",
        seed=0,
        sampler="scoretable",
        refresh_size=8,
        telemetry=True,
    )
    base.update(kw)
    return TrainConfig(**base)


def run_steps(t, n):
    m = None
    for _ in range(n):
        t.state, m = t.train_step(
            t.state, t._step_x, t._step_y, t.dataset.shard_indices
        )
    return m


class TestHistogram:
    """log_bin_histogram (jnp, in-graph) vs log_bin_histogram_np: the
    flight recorder and report render what the numpy reference says the
    step emitted — the two must be BIT-identical, not close."""

    EDGE_PAIRS = [(SCORE_HIST_LO, SCORE_HIST_HI),
                  (WEIGHT_HIST_LO, WEIGHT_HIST_HI)]

    def test_bit_match_vs_numpy_lognormal(self, rng):
        for lo, hi in self.EDGE_PAIRS:
            for size, sigma in [(1, 1.0), (57, 2.0), (4096, 6.0)]:
                x = rng.lognormal(mean=0.0, sigma=sigma,
                                  size=size).astype(np.float32)
                want = log_bin_histogram_np(x, lo, hi)
                got = np.asarray(log_bin_histogram(jnp.asarray(x), lo, hi))
                np.testing.assert_array_equal(got, want)
                assert int(got.sum()) == size

    def test_bit_match_on_edges_and_clamps(self):
        for lo, hi in self.EDGE_PAIRS:
            edges = hist_bin_edges(lo, hi).astype(np.float32)
            x = np.concatenate([
                edges,                      # every bin boundary exactly
                np.float32([0.0, lo / 10, lo, hi, hi * 10, 1.0, np.inf]),
            ])
            want = log_bin_histogram_np(x, lo, hi)
            got = np.asarray(log_bin_histogram(jnp.asarray(x), lo, hi))
            np.testing.assert_array_equal(got, want)
            # Clamp-into-end-bins: counts always total the population.
            assert int(got.sum()) == x.size

    def test_below_lo_and_above_hi_land_in_end_bins(self):
        h = log_bin_histogram_np(np.float32([1e-30, 0.0]), 1e-6, 1e2)
        assert h[0] == 2 and h.sum() == 2
        h = log_bin_histogram_np(np.float32([1e30, np.inf]), 1e-6, 1e2)
        assert h[-1] == 2 and h.sum() == 2

    def test_hist_keys_shape_and_registration(self):
        from mercury_tpu.obs.registry import METRIC_KEYS

        for family in ("score_hist", "w_hist"):
            keys = hist_keys(family)
            assert len(keys) == HIST_BINS
            assert keys[0] == f"sampler_dist/{family}/b00"
            assert keys[-1] == f"sampler_dist/{family}/b15"
            for k in keys:
                assert k in METRIC_KEYS, k
        for k in ("sampler_dist/var_ratio", "sampler_dist/gini",
                  "sampler_dist/frac_never_selected",
                  "sampler_dist/class_share_min",
                  "sampler_dist/class_share_max",
                  "sampler_dist/class_starved", "sampler_dist/bias_chi2",
                  "sampler_dist/bias_ok"):
            assert k in METRIC_KEYS, k

    def test_edges_are_log_spaced(self):
        e = hist_bin_edges(1e-6, 1e2)
        assert e.shape == (HIST_BINS + 1,)
        np.testing.assert_allclose(e[0], 1e-6, rtol=1e-12)
        np.testing.assert_allclose(e[-1], 1e2, rtol=1e-12)
        ratios = e[1:] / e[:-1]
        np.testing.assert_allclose(ratios, ratios[0], rtol=1e-9)

    def test_sparkline_renders(self):
        assert sparkline([0, 0, 0]) == "▁▁▁"
        s = sparkline([0, 1, 2, 4, 8])
        assert len(s) == 5 and s[-1] == "█"
        assert sparkline([]) == ""


class TestLedgerDerivations:
    def test_global_counts_sum_duplicates(self):
        # Sample 2 owns three slots (cyclic tiling + cross-worker): its
        # counts SUM — additive, unlike the score carry's last-wins.
        sidx = np.array([[2, 0, 2], [1, 2, 3]])
        counts = np.array([[5, 1, 7], [2, 3, 4]])
        out = ledger_global_counts(counts, sidx, 5)
        np.testing.assert_array_equal(out, [1, 2, 15, 4, 0])

    def test_gini_uniform_and_concentrated(self):
        assert gini(np.full(100, 7)) == pytest.approx(0.0, abs=1e-12)
        one_hot = np.zeros(100)
        one_hot[3] = 1000
        assert gini(one_hot) == pytest.approx(0.99, abs=1e-9)
        assert gini(np.zeros(10)) == 0.0
        assert gini(np.array([])) == 0.0

    def test_class_spread_flags_starvation(self):
        labels = np.array([0] * 50 + [1] * 50)
        even = np.ones(100)
        s = class_spread(even, labels, num_classes=2)
        assert s["class_share_min"] == pytest.approx(1.0)
        assert s["class_share_max"] == pytest.approx(1.0)
        assert s["class_starved"] == 0.0
        starved = np.concatenate([np.full(50, 99.0), np.full(50, 1.0)])
        s = class_spread(starved, labels, num_classes=2,
                         starvation_share=0.2)
        assert s["class_starved"] == 1.0
        assert s["class_share_min"] == pytest.approx(0.02)

    def test_bias_audit_passes_faithful_draws(self, rng):
        W, L, draws = 2, 64, 20_000
        probs = rng.dirichlet(np.full(L, 5.0), size=W)
        counts = np.stack([rng.multinomial(draws, probs[w])
                           for w in range(W)])
        audit = bias_audit(counts, probs)
        assert audit["bias_ok"] == 1.0
        # Multinomial noise keeps the per-dof stat near 1.
        assert audit["bias_chi2"] < 5.0

    def test_bias_audit_flags_tilted_sampler(self, rng):
        # The table claims uniform; the draws actually came from a sharply
        # tilted distribution — the audit must flag the drift.
        L, draws = 64, 20_000
        claimed = np.full((1, L), 1.0 / L)
        tilted = np.linspace(1.0, 20.0, L)
        tilted /= tilted.sum()
        counts = rng.multinomial(draws, tilted)[None]
        audit = bias_audit(counts, claimed)
        assert audit["bias_ok"] == 0.0
        assert audit["bias_chi2"] > 5.0

    def test_bias_audit_empty_ledger_is_ok(self):
        audit = bias_audit(np.zeros((2, 8)), np.full((2, 8), 1 / 8))
        assert audit == {"bias_chi2": 0.0, "bias_ok": 1.0}

    def test_table_probs_np_matches_traced(self):
        from mercury_tpu.sampling.scoretable import table_probs

        scores = np.abs(np.random.default_rng(3).normal(
            size=(4, 33))).astype(np.float32)
        ema = np.float32([0.5, 1.0, 2.0, 0.1])
        want = np.stack([
            np.asarray(table_probs(jnp.asarray(scores[w]),
                                   jnp.float32(ema[w]), 0.5))
            for w in range(4)
        ])
        got = table_probs_np(scores, ema, 0.5)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-12)


class TestVarianceProbe:
    """sampler_dist/var_ratio follows benchmarks/grad_variance.py's
    convention: a ratio of (IS / uniform) gradient second moments,
    ``< 1`` ⇔ importance sampling wins. Cross-validated on CPU against
    the analytic second moments the benchmark's estimators converge
    to."""

    def _population(self, rng, L=512):
        # Local fixed-seed generators (not the shared session rng): the
        # adversarial estimator is heavy-tailed, so the assertions must
        # not depend on how much of the shared stream earlier tests ate.
        g = rng.lognormal(mean=0.0, sigma=1.0, size=L).astype(np.float32)
        return g

    def _exact_ratio(self, g, p):
        # E_p[(g/(L·p))²] / E_unif[g²] — the single-draw second-moment
        # ratio both grad_variance.py estimators report (mean terms
        # cancel; 1803.00942 §3).
        L = g.size
        m_is = float(np.sum(p * (g / (L * p)) ** 2))
        m_unif = float(np.mean(g**2))
        return m_is / m_unif

    def _probe_on_batch(self, rng, g, p, batch=8192):
        sel = rng.choice(g.size, size=batch, p=p)
        scaled = (p * g.size)[sel]
        return float(variance_probe_ratio(g[sel], scaled))

    def test_uniform_weights_give_exactly_one(self):
        g = jnp.asarray([0.5, 1.0, 2.0, 4.0], jnp.float32)
        sp = jnp.ones((4,), jnp.float32)  # L·p == 1 ⇔ uniform draw
        assert float(variance_probe_ratio(g, sp)) == 1.0

    def test_gradnorm_proportional_sampling_wins(self):
        rng = np.random.default_rng(11)
        g = self._population(rng)
        p = g / g.sum()  # the 1803.00942 optimal proposal
        exact = self._exact_ratio(g, p)
        probe = self._probe_on_batch(rng, g, p)
        assert exact < 1.0
        assert probe < 1.0  # same side of the gate as the benchmark
        # p ∝ g bounds the weights, so the estimate concentrates.
        np.testing.assert_allclose(probe, exact, rtol=0.15)

    def test_adversarial_sampling_loses(self):
        rng = np.random.default_rng(12)
        g = self._population(rng)
        p = (1.0 / g) / (1.0 / g).sum()  # oversample the SMALL gradients
        exact = self._exact_ratio(g, p)
        probe = self._probe_on_batch(rng, g, p)
        # Sign agreement only: w² ∝ g⁴ makes this estimator heavy-tailed,
        # so the gate SIDE (the benchmark's convention) is the claim.
        assert exact > 1.0
        assert probe > 1.0

    def test_ordering_matches_benchmark_convention(self):
        # good proposal < uniform (== 1) < adversarial proposal — the
        # ordering grad_variance.py's ratio_* columns encode.
        rng = np.random.default_rng(13)
        g = self._population(rng)
        good = self._probe_on_batch(rng, g, g / g.sum())
        bad = self._probe_on_batch(rng, g, (1 / g) / (1 / g).sum())
        assert good < 1.0 < bad


class TestAnomalyTriggers:
    """The three sampler-health triggers, driven with synthesized records
    (the test_trace_anomaly.py idiom — no model, fully deterministic)."""

    def _record(self, step, **extra):
        r = {"step": float(step), "time": 1000.0 + step, "train/loss": 1.0}
        r.update(extra)
        return r

    def test_selection_collapse_attaches_histograms(self, tmp_path):
        from mercury_tpu.obs.anomaly import AnomalyEngine

        eng = AnomalyEngine(ring_steps=4, gini_max=0.8,
                            dump_dir=str(tmp_path))
        hist = {k: float(i) for i, k in enumerate(hist_keys("score_hist"))}
        eng.observe_record(self._record(
            3, **{"sampler_dist/gini": 0.95,
                  "sampler_dist/frac_never_selected": 0.5}, **hist))
        assert eng.trigger_counts == {"selection_collapse": 1}
        (path,) = eng.dumps
        doc = json.load(open(path))
        detail = doc["trigger"]["detail"]
        assert detail["gini"] == 0.95
        assert detail["frac_never_selected"] == 0.5
        for k, v in hist.items():
            assert detail[k] == v

    def test_selection_collapse_disarmed_by_default(self):
        from mercury_tpu.obs.anomaly import AnomalyEngine

        eng = AnomalyEngine(ring_steps=4)
        eng.observe_record(self._record(1, **{"sampler_dist/gini": 0.999}))
        assert eng.triggers == 0

    def test_class_starvation(self):
        from mercury_tpu.obs.anomaly import AnomalyEngine

        eng = AnomalyEngine(ring_steps=4, starved_classes=1.0)
        eng.observe_record(self._record(
            1, **{"sampler_dist/class_starved": 0.0}))
        assert eng.triggers == 0
        eng.observe_record(self._record(
            2, **{"sampler_dist/class_starved": 2.0,
                  "sampler_dist/class_share_min": 0.01}))
        assert eng.trigger_counts == {"class_starvation": 1}

    def test_is_losing_needs_consecutive_breaches(self):
        from mercury_tpu.obs.anomaly import AnomalyEngine

        eng = AnomalyEngine(ring_steps=8, var_ratio_patience=3)
        for s in (1, 2):
            eng.observe_record(self._record(
                s, **{"sampler_dist/var_ratio": 1.5}))
        assert eng.triggers == 0
        # A genuine healthy reading (< 1) resets the streak...
        eng.observe_record(self._record(
            3, **{"sampler_dist/var_ratio": 0.7}))
        for s in (4, 5):
            eng.observe_record(self._record(
                s, **{"sampler_dist/var_ratio": 1.2}))
        assert eng.triggers == 0
        eng.observe_record(self._record(
            6, **{"sampler_dist/var_ratio": 1.2}))
        assert eng.trigger_counts == {"is_losing": 1}

    def test_is_losing_sentinel_neither_counts_nor_resets(self):
        from mercury_tpu.obs.anomaly import AnomalyEngine

        eng = AnomalyEngine(ring_steps=8, var_ratio_patience=2)
        eng.observe_record(self._record(
            1, **{"sampler_dist/var_ratio": 1.5}))
        # Off-cadence sentinel records (-1.0) must not break the streak.
        eng.observe_record(self._record(
            2, **{"sampler_dist/var_ratio": -1.0}))
        eng.observe_record(self._record(
            3, **{"sampler_dist/var_ratio": 1.5}))
        assert eng.trigger_counts == {"is_losing": 1}


class TestLedgerTrainer:
    """The ledger counts the draws the step ACTUALLY trained on — pinned
    exact over 200 steps by replaying the async body's draw chain
    (decay → normalize → inverse-CDF on the pre-step table with the
    step's own key split) on the host."""

    def test_body_ledger_matches_replayed_draws_200_steps(self, mesh):
        from mercury_tpu.sampling.scoretable import (
            decay_scores,
            table_draw_inverse_cdf,
            table_probs,
        )

        cfg = table_cfg(refresh_mode="async", scorer_workers=1,
                        snapshot_every=2)
        t = Trainer(cfg, mesh=mesh)
        try:
            W = cfg.world_size
            L = int(t.dataset.shard_indices.shape[1])
            assert t.state.sel_counts.shape == (W, L)
            assert int(np.asarray(t.state.sel_counts).sum()) == 0
            expected = np.zeros((W, L), np.int64)
            for _ in range(200):
                scores = np.asarray(t.state.scoretable.scores)
                ema = np.asarray(t.state.ema.value)
                keys = jax.random.wrap_key_data(
                    jnp.asarray(np.asarray(
                        jax.random.key_data(t.state.rng))))
                for w in range(W):
                    # The body's rng_t 8-way split: position 2 is k_sel.
                    k_sel = jax.random.split(keys[w], 8)[2]
                    dec = decay_scores(
                        jnp.asarray(scores[w], jnp.float32),
                        jnp.float32(ema[w]), cfg.table_decay)
                    probs = table_probs(dec, jnp.float32(ema[w]),
                                        cfg.is_alpha)
                    sel = np.asarray(table_draw_inverse_cdf(
                        k_sel, probs, cfg.batch_size))
                    expected[w] += np.bincount(sel, minlength=L)
                run_steps(t, 1)
            got = np.asarray(t.state.sel_counts)
            np.testing.assert_array_equal(got, expected.astype(np.int32))
            assert int(got.sum()) == 200 * W * cfg.batch_size

            # The monitor derives from exactly this ledger.
            mon = SamplerHealthMonitor(
                np.asarray(t.dataset.shard_indices),
                np.asarray(t.dataset.y_train),
                t.dataset.num_classes, cfg.is_alpha)
            stats = mon.stats(t.state)
            gcounts = ledger_global_counts(
                got, np.asarray(t.dataset.shard_indices),
                int(np.asarray(t.dataset.y_train).size))
            assert stats["sampler_dist/frac_never_selected"] == (
                pytest.approx(float(np.mean(gcounts == 0))))
            assert stats["sampler_dist/gini"] == pytest.approx(
                gini(gcounts))
            assert 0.0 <= stats["sampler_dist/bias_ok"] <= 1.0
        finally:
            t.close()

    def test_telemetry_off_has_no_ledger(self, mesh):
        t = Trainer(table_cfg(telemetry=False, steps_per_epoch=2),
                    mesh=mesh)
        try:
            assert t.state.sel_counts is None
            run_steps(t, 2)
            assert t.state.sel_counts is None
        finally:
            t.close()


class TestHostStreamLedger:
    """Under ``data_placement="host_stream"`` the trained slots are the
    pending-selection ring front — host-readable BEFORE the step runs, so
    the expected counts need no replay at all."""

    def _hs_cfg(self, **kw):
        return table_cfg(world_size=1, data_placement="host_stream",
                         prefetch_depth=2, **kw)

    def test_sync_ledger_matches_ring_front_200_steps(self, mesh1):
        cfg = self._hs_cfg()
        t = Trainer(cfg, mesh=mesh1)
        try:
            L = int(t.dataset.shard_indices.shape[1])
            expected = np.zeros((1, L), np.int64)
            for _ in range(200):
                front = np.asarray(t.state.pending_sel.slots)[:, 0, :]
                # Sync layout: rows 0:R are the refresh window (never
                # trained), rows R: are the train rows.
                train_rows = front[:, cfg.refresh_size:]
                for w in range(train_rows.shape[0]):
                    expected[w] += np.bincount(train_rows[w], minlength=L)
                t._host_stream_step()
            np.testing.assert_array_equal(
                np.asarray(t.state.sel_counts),
                expected.astype(np.int32))
            assert int(expected.sum()) == 200 * cfg.batch_size
        finally:
            t.close()

    @pytest.mark.slow  # async+host_stream compile cost (matrix-tier call)
    def test_async_ledger_counts_full_ring_front(self, mesh1):
        cfg = self._hs_cfg(refresh_mode="async", scorer_workers=1,
                           snapshot_every=2, steps_per_epoch=30)
        t = Trainer(cfg, mesh=mesh1)
        try:
            L = int(t.dataset.shard_indices.shape[1])
            expected = np.zeros((1, L), np.int64)
            for _ in range(30):
                front = np.asarray(t.state.pending_sel.slots)[:, 0, :]
                # Async: the stream carries ONLY train rows — all of them
                # count.
                for w in range(front.shape[0]):
                    expected[w] += np.bincount(front[w], minlength=L)
                t._host_stream_step()
            np.testing.assert_array_equal(
                np.asarray(t.state.sel_counts),
                expected.astype(np.int32))
        finally:
            t.close()


class TestLedgerDurability:
    def test_checkpoint_roundtrip_preserves_counts(self, mesh, tmp_path):
        cfg = table_cfg(steps_per_epoch=8, checkpoint_dir=str(tmp_path))
        t = Trainer(cfg, mesh=mesh)
        try:
            run_steps(t, 3)
            t.save()
            at_save = np.asarray(t.state.sel_counts).copy()
            run_steps(t, 3)
            want_final = np.asarray(t.state.sel_counts).copy()
        finally:
            t.close()
        assert int(at_save.sum()) == 3 * 4 * cfg.batch_size

        t2 = Trainer(cfg, mesh=mesh)
        try:
            t2.restore()
            assert int(t2.state.step) == 3
            np.testing.assert_array_equal(
                np.asarray(t2.state.sel_counts), at_save)
            # The continued trajectory re-accumulates identically.
            run_steps(t2, 3)
            np.testing.assert_array_equal(
                np.asarray(t2.state.sel_counts), want_final)
        finally:
            t2.close()

    def test_pre_ledger_checkpoint_restores_with_fresh_zeros(
            self, mesh, tmp_path):
        """Upgrade shim: a checkpoint written with ``telemetry=False``
        (no ``sel_counts`` entry) restores into a ledger-bearing trainer
        via the elastic path — params carry, the ledger starts at
        zero."""
        old = Trainer(table_cfg(telemetry=False, steps_per_epoch=4,
                                checkpoint_dir=str(tmp_path)), mesh=mesh)
        try:
            run_steps(old, 2)
            old.save()
            want = np.asarray(
                jax.tree_util.tree_leaves(old.state.params)[0])
        finally:
            old.close()

        t = Trainer(table_cfg(steps_per_epoch=4,
                              checkpoint_dir=str(tmp_path)), mesh=mesh)
        try:
            assert t.restore_elastic() == 2
            got = np.asarray(jax.tree_util.tree_leaves(t.state.params)[0])
            np.testing.assert_array_equal(want, got)
            counts = np.asarray(t.state.sel_counts)
            assert counts.shape[0] == 4
            assert int(counts.sum()) == 0
            run_steps(t, 1)  # the fresh ledger accumulates from here
            assert int(np.asarray(t.state.sel_counts).sum()) == (
                4 * t.config.batch_size)
        finally:
            t.close()


@pytest.mark.slow  # parallelism-matrix compile cost (test_elastic.py tier)
class TestLedgerElastic:
    def test_shrink_w8_to_w4_carries_exact_per_sample_counts(
            self, tmp_path):
        """W=8 → W′=4 ``restore_elastic``: the GLOBAL per-sample counts
        (cyclic-tiling duplicates summed) carry exactly — the additive
        carry, not the scores' last-wins."""
        t1 = Trainer(table_cfg(world_size=8, steps_per_epoch=5,
                               checkpoint_dir=str(tmp_path)),
                     mesh=host_cpu_mesh(8))
        try:
            run_steps(t1, 5)
            t1.save()
            n = int(np.asarray(t1.dataset.y_train).size)
            want_global = ledger_global_counts(
                np.asarray(t1.state.sel_counts),
                np.asarray(t1.dataset.shard_indices), n)
        finally:
            t1.close()
        assert int(want_global.sum()) == 5 * 8 * 8  # steps · W · batch

        t2 = Trainer(table_cfg(world_size=4, steps_per_epoch=5,
                               checkpoint_dir=str(tmp_path)),
                     mesh=host_cpu_mesh(4))
        try:
            assert t2.restore_elastic() == 5
            got_global = ledger_global_counts(
                np.asarray(t2.state.sel_counts),
                np.asarray(t2.dataset.shard_indices), n)
            np.testing.assert_array_equal(got_global, want_global)
        finally:
            t2.close()


class TestHeartbeatAndTolerances:
    def test_is_active_in_heartbeat_and_tolerances(self):
        from mercury_tpu.obs.writer import HeartbeatSink

        assert "sampler/is_active" in HeartbeatSink._KEYS
        tol_path = os.path.join(
            os.path.dirname(__file__), os.pardir, "mercury_tpu", "obs",
            "report_tolerances.json")
        rules = json.load(open(tol_path))["rules"]
        assert "sampler/is_active" in rules
        assert rules["sampler_dist/gini"]["direction"] == "lower_better"
        assert (rules["sampler_dist/frac_never_selected"]["direction"]
                == "lower_better")
