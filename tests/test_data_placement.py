"""data_placement="sharded": each worker's shard rows materialized as
[W, L, ...] arrays sharded over the data axis — per-device train-data
memory is one shard row instead of the full dataset (the scaling-past-
CIFAR path; parity with ``load_partition_data_distributed_cifar10``,
``cifar10/data_loader.py:214-245``). Must be numerically IDENTICAL to the
replicated placement: the sharded gather x_shard[0][slots] reads the same
bytes as the replicated x_train[shard_indices[0][slots]]."""

import jax
import numpy as np
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.trainer import Trainer

pytestmark = pytest.mark.slow  # parallelism-matrix compile cost blows the tier-1 budget


@pytest.fixture(scope="module")
def mesh():
    return host_cpu_mesh(4)


def cfg(**kw):
    base = dict(model="smallcnn", dataset="synthetic", world_size=4,
                batch_size=4, presample_batches=2, steps_per_epoch=3,
                num_epochs=1, eval_every=0, log_every=0,
                compute_dtype="float32", seed=0)
    base.update(kw)
    return TrainConfig(**base)


def steps(tr, n):
    out = []
    for _ in range(n):
        tr.state, m = tr.train_step(
            tr.state, tr._step_x, tr._step_y, tr.dataset.shard_indices)
        out.append(float(m["train/loss"]))
    return out


class TestShardedPlacement:
    def test_matches_replicated_bitwise(self, mesh):
        rep = Trainer(cfg(), mesh=mesh)
        shd = Trainer(cfg(data_placement="sharded"), mesh=mesh)
        np.testing.assert_array_equal(steps(rep, 3), steps(shd, 3))

    def test_per_device_memory_is_shard_sized(self, mesh):
        shd = Trainer(cfg(data_placement="sharded"), mesh=mesh)
        full = np.asarray(shd.dataset.x_train).nbytes
        per_dev = shd._step_x.addressable_shards[0].data.nbytes
        # One cyclically-tiled shard row ≈ max-shard/N of the dataset —
        # strictly below half even with Dirichlet skew at W=4.
        assert per_dev < 0.5 * full, (per_dev, full)
        # The full train array stays host-side (numpy), not on a device.
        assert isinstance(shd.dataset.x_train, np.ndarray)

    def test_fit_eval_and_scan_compose(self, mesh):
        tr = Trainer(cfg(data_placement="sharded", scan_steps=3), mesh=mesh)
        out = tr.fit(num_epochs=1)
        assert np.isfinite(out["test/eval_loss"])
        assert int(tr.state.step) == 3

    def test_groupwise_and_pipelined_compose(self, mesh):
        for extra in ({"sampler": "groupwise"}, {"pipelined_scoring": True}):
            rep = Trainer(cfg(**extra), mesh=mesh)
            shd = Trainer(cfg(data_placement="sharded", **extra), mesh=mesh)
            np.testing.assert_array_equal(steps(rep, 2), steps(shd, 2))

    def test_unknown_placement_rejected(self, mesh):
        with pytest.raises(ValueError, match="data_placement"):
            Trainer(cfg(data_placement="nope"), mesh=mesh)
