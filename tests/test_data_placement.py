"""Non-replicated data placements.

``data_placement="sharded"``: each worker's shard rows materialized as
[W, L, ...] arrays sharded over the data axis — per-device train-data
memory is one shard row instead of the full dataset (the scaling-past-
CIFAR path; parity with ``load_partition_data_distributed_cifar10``,
``cifar10/data_loader.py:214-245``). Must be numerically IDENTICAL to the
replicated placement: the sharded gather x_shard[0][slots] reads the same
bytes as the replicated x_train[shard_indices[0][slots]].

``data_placement="host_stream"``: pixels never resident on device — the
in-graph selection runs ``prefetch_depth`` steps ahead and a background
thread streams each selected batch in (``data/stream.py``,
``train/step.py::hs_body``). The uniform and pool samplers must be
BIT-identical to replicated (the lookahead replays the same RNG chain);
the scoretable sampler accepts depth-step-stale selection by design, so
it gets a smoke + telemetry check instead."""

import jax
import numpy as np
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def mesh():
    return host_cpu_mesh(4)


@pytest.fixture(scope="module")
def mesh1():
    return host_cpu_mesh(1)


def cfg(**kw):
    base = dict(model="smallcnn", dataset="synthetic", world_size=4,
                batch_size=4, presample_batches=2, steps_per_epoch=3,
                num_epochs=1, eval_every=0, log_every=0,
                compute_dtype="float32", seed=0)
    base.update(kw)
    return TrainConfig(**base)


def steps(tr, n):
    out = []
    for _ in range(n):
        tr.state, m = tr.train_step(
            tr.state, tr._step_x, tr._step_y, tr.dataset.shard_indices)
        out.append(float(m["train/loss"]))
    return out


def stream_steps(tr, n):
    return [float(tr._host_stream_step()["train/loss"]) for _ in range(n)]


class TestShardedPlacement:
    # parallelism-matrix compile cost blows the tier-1 budget
    pytestmark = pytest.mark.slow

    def test_matches_replicated_bitwise(self, mesh):
        rep = Trainer(cfg(), mesh=mesh)
        shd = Trainer(cfg(data_placement="sharded"), mesh=mesh)
        np.testing.assert_array_equal(steps(rep, 3), steps(shd, 3))

    def test_per_device_memory_is_shard_sized(self, mesh):
        shd = Trainer(cfg(data_placement="sharded"), mesh=mesh)
        full = np.asarray(shd.dataset.x_train).nbytes
        per_dev = shd._step_x.addressable_shards[0].data.nbytes
        # One cyclically-tiled shard row ≈ max-shard/N of the dataset —
        # strictly below half even with Dirichlet skew at W=4.
        assert per_dev < 0.5 * full, (per_dev, full)
        # The full train array stays host-side (numpy), not on a device.
        assert isinstance(shd.dataset.x_train, np.ndarray)

    def test_fit_eval_and_scan_compose(self, mesh):
        tr = Trainer(cfg(data_placement="sharded", scan_steps=3), mesh=mesh)
        out = tr.fit(num_epochs=1)
        assert np.isfinite(out["test/eval_loss"])
        assert int(tr.state.step) == 3

    def test_groupwise_and_pipelined_compose(self, mesh):
        for extra in ({"sampler": "groupwise"}, {"pipelined_scoring": True}):
            rep = Trainer(cfg(**extra), mesh=mesh)
            shd = Trainer(cfg(data_placement="sharded", **extra), mesh=mesh)
            np.testing.assert_array_equal(steps(rep, 2), steps(shd, 2))

    def test_unknown_placement_rejected(self, mesh):
        with pytest.raises(ValueError, match="data_placement"):
            Trainer(cfg(data_placement="nope"), mesh=mesh)


def hs_cfg(**kw):
    base = dict(model="smallcnn", dataset="synthetic", world_size=1,
                batch_size=8, presample_batches=2, steps_per_epoch=8,
                num_epochs=1, eval_every=0, log_every=0, heartbeat_every=0,
                checkpoint_every=0, compute_dtype="float32", seed=0)
    base.update(kw)
    return TrainConfig(**base)


class TestHostStream:
    """Tier-1: 1-device CPU mesh, small model — one compile per sampler."""

    # ISSUE acceptance: loss-trajectory-identical for >= 3 steps after
    # warmup. depth+4 = 6 steps covers cold-start AND steady state.
    N_STEPS = 6

    def _pair(self, mesh1, **kw):
        rep = Trainer(hs_cfg(**kw), mesh=mesh1)
        hs = Trainer(hs_cfg(data_placement="host_stream", prefetch_depth=2,
                            **kw), mesh=mesh1)
        return rep, hs

    def test_uniform_bitwise_identical(self, mesh1):
        rep, hs = self._pair(mesh1, use_importance_sampling=False)
        try:
            np.testing.assert_array_equal(
                steps(rep, self.N_STEPS), stream_steps(hs, self.N_STEPS))
        finally:
            hs.close()

    def test_pool_bitwise_identical(self, mesh1):
        rep, hs = self._pair(mesh1)
        try:
            np.testing.assert_array_equal(
                steps(rep, self.N_STEPS), stream_steps(hs, self.N_STEPS))
        finally:
            hs.close()

    def test_scoretable_smoke_and_telemetry(self, mesh1):
        hs = Trainer(hs_cfg(data_placement="host_stream", prefetch_depth=2,
                            sampler="scoretable"), mesh=mesh1)
        try:
            losses = stream_steps(hs, self.N_STEPS)
            assert np.all(np.isfinite(losses)), losses
            stats = hs._stream_pipe.stats()
            assert set(stats) == {"data/stall_s", "data/queue_depth",
                                  "data/h2d_bytes",
                                  "threads/queue_depth/prefetch"}
            # 6 batches streamed: prime pushed 2, each step pushed 1 more.
            assert stats["data/h2d_bytes"] > 0
            assert hs._stream_pipe.pops == self.N_STEPS
        finally:
            hs.close()

    def test_fit_streams_and_logs(self, mesh1):
        hs = Trainer(hs_cfg(data_placement="host_stream", steps_per_epoch=3),
                     mesh=mesh1)
        try:
            out = hs.fit(num_epochs=1)
            assert np.isfinite(out["test/eval_loss"])
            assert int(hs.state.step) == 3
        finally:
            hs.close()

    @pytest.mark.parametrize("bad", [
        dict(prefetch_depth=0),
        dict(pipelined_scoring=True),
        dict(score_refresh_every=2),
        dict(sampler="groupwise"),
        dict(scan_steps=3),
    ])
    def test_incompatible_configs_rejected(self, mesh1, bad):
        with pytest.raises(ValueError):
            Trainer(hs_cfg(data_placement="host_stream", **bad), mesh=mesh1)

    def test_restore_elastic_resumes_mid_epoch(self, tmp_path):
        """W=2 → W=1 elastic restore mid-stream: the shard-stream cursor
        carries as an epoch fraction (``config.stream_checkpoint_cursor``),
        the lookahead ring re-primes for the new topology, and training
        resumes with finite losses."""
        t1 = Trainer(hs_cfg(data_placement="host_stream", world_size=2,
                            checkpoint_dir=str(tmp_path)),
                     mesh=host_cpu_mesh(2))
        try:
            stream_steps(t1, 3)
            t1.save()
        finally:
            t1.close()

        t2 = Trainer(hs_cfg(data_placement="host_stream", world_size=1,
                            checkpoint_dir=str(tmp_path)),
                     mesh=host_cpu_mesh(1))
        try:
            fresh_cursor = np.asarray(t2.state.stream.cursor).copy()
            assert t2.restore_elastic() == 3
            assert int(t2.state.step) == 3
            carried = np.asarray(t2.state.stream.cursor)
            # A fresh trainer primes its ring from cursor 0; the elastic
            # carry resumes the shard sweep mid-epoch, so the re-primed
            # cursor sits strictly past the fresh-primed one.
            assert np.all(carried > fresh_cursor), (carried, fresh_cursor)
            losses = stream_steps(t2, 3)
            assert np.all(np.isfinite(losses)), losses
        finally:
            t2.close()

        # Gate off: stream_checkpoint_cursor=False restarts the sweep
        # near the epoch start (only the init + restore primes have
        # advanced it), well short of the mid-epoch carried cursor.
        t3 = Trainer(hs_cfg(data_placement="host_stream", world_size=1,
                            stream_checkpoint_cursor=False,
                            checkpoint_dir=str(tmp_path)),
                     mesh=host_cpu_mesh(1))
        try:
            t3.restore_elastic()
            assert np.all(np.asarray(t3.state.stream.cursor) < carried)
        finally:
            t3.close()

    def test_restore_elastic_carries_scoretable(self, tmp_path):
        """W=2 → W=1 elastic restore repartitions the per-sample score
        table by new worker ownership: every sample the old run owned
        keeps its learned score bit-exactly under the new ``[W', L']``
        index matrix (samples nobody owned warm-start at the EMA mean)."""
        from mercury_tpu.train.elastic import _shard_index_matrix

        t1 = Trainer(hs_cfg(data_placement="host_stream", world_size=2,
                            sampler="scoretable",
                            checkpoint_dir=str(tmp_path)),
                     mesh=host_cpu_mesh(2))
        try:
            stream_steps(t1, 3)
            t1.save()
            old_scores = np.asarray(
                jax.device_get(t1.state.scoretable.scores), np.float32)
            ema_val = float(np.mean(np.asarray(t1.state.ema.value)))
        finally:
            t1.close()
        # The old run actually trained its table (the in-step refresh ran)
        # — otherwise the carry equality below would hold vacuously.
        assert not np.all(old_scores == old_scores.reshape(-1)[0])

        t2 = Trainer(hs_cfg(data_placement="host_stream", world_size=1,
                            sampler="scoretable",
                            checkpoint_dir=str(tmp_path)),
                     mesh=host_cpu_mesh(1))
        try:
            assert t2.restore_elastic() == 3
            old_sidx = _shard_index_matrix(t2, 2)
            new_sidx = _shard_index_matrix(t2, 1)
            n = int(np.asarray(t2.dataset.y_train).size)
            want = np.full((n,), ema_val, np.float32)
            want[old_sidx.reshape(-1)] = old_scores.reshape(-1)
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(t2.state.scoretable.scores)),
                want[new_sidx])
            losses = stream_steps(t2, 2)
            assert np.all(np.isfinite(losses)), losses
        finally:
            t2.close()

    def test_local_shard_mode_bitwise_identical(self, mesh1):
        """stream_shard_mode='local' forced in a single-process run takes
        the per-host slab + callback-assembly path (the multi-controller
        code) and must stay bit-identical to the replicated full-slab
        path."""
        rep = Trainer(hs_cfg(), mesh=mesh1)
        hs = Trainer(hs_cfg(data_placement="host_stream", prefetch_depth=2,
                            stream_shard_mode="local"), mesh=mesh1)
        try:
            assert hs._stream_local_workers is not None
            np.testing.assert_array_equal(
                steps(rep, self.N_STEPS), stream_steps(hs, self.N_STEPS))
        finally:
            hs.close()

    def test_bad_shard_mode_rejected(self, mesh1):
        with pytest.raises(ValueError, match="stream_shard_mode"):
            Trainer(hs_cfg(data_placement="host_stream",
                           stream_shard_mode="nope"), mesh=mesh1)


class TestFusedInput:
    """fused_input=True: the ``ops.augment_normalize_pallas`` ingest must
    replay the unfused normalize→augment trajectory BIT-identically — the
    kernel replays ``augment_batch``'s exact RNG consumption, so fusing is
    a pure lowering change, never a numerics change. Tier-1 pins the
    1-device stream paths; the world-4 matrix entry lives in
    ``TestHostStreamMatrix`` (slow)."""

    N_STEPS = 6

    def test_uniform_stream_fused_matches_replicated_unfused(self, mesh1):
        rep = Trainer(hs_cfg(use_importance_sampling=False), mesh=mesh1)
        hs = Trainer(hs_cfg(data_placement="host_stream", prefetch_depth=2,
                            fused_input=True,
                            use_importance_sampling=False), mesh=mesh1)
        try:
            np.testing.assert_array_equal(
                steps(rep, self.N_STEPS), stream_steps(hs, self.N_STEPS))
        finally:
            hs.close()

    def test_pool_stream_fused_matches_replicated_unfused(self, mesh1):
        rep = Trainer(hs_cfg(), mesh=mesh1)
        hs = Trainer(hs_cfg(data_placement="host_stream", prefetch_depth=2,
                            fused_input=True), mesh=mesh1)
        try:
            np.testing.assert_array_equal(
                steps(rep, self.N_STEPS), stream_steps(hs, self.N_STEPS))
        finally:
            hs.close()

    def test_scoretable_stream_fused_matches_unfused(self, mesh1):
        """Streamed scoretable is depth-stale vs replicated by design, so
        the invariant here is fused-stream == unfused-stream."""
        a = Trainer(hs_cfg(data_placement="host_stream", prefetch_depth=2,
                           sampler="scoretable"), mesh=mesh1)
        b = Trainer(hs_cfg(data_placement="host_stream", prefetch_depth=2,
                           sampler="scoretable", fused_input=True),
                    mesh=mesh1)
        try:
            np.testing.assert_array_equal(
                stream_steps(a, self.N_STEPS), stream_steps(b, self.N_STEPS))
        finally:
            a.close()
            b.close()

    @pytest.mark.parametrize("bad", [
        dict(cutout=True),
        dict(augmentation="iid"),
    ])
    def test_unfusable_configs_rejected(self, mesh1, bad):
        with pytest.raises(ValueError, match="fused_input"):
            Trainer(hs_cfg(fused_input=True, **bad), mesh=mesh1)


class TestHostStreamMatrix:
    """4-way parallelism matrix — compile cost belongs in the slow tier."""

    pytestmark = pytest.mark.slow

    @pytest.mark.parametrize("kw", [
        dict(use_importance_sampling=False),
        dict(),  # pool
    ])
    def test_w4_bitwise_identical(self, mesh, kw):
        rep = Trainer(cfg(steps_per_epoch=8, **kw), mesh=mesh)
        hs = Trainer(cfg(data_placement="host_stream", prefetch_depth=2,
                         steps_per_epoch=8, **kw), mesh=mesh)
        try:
            np.testing.assert_array_equal(steps(rep, 6), stream_steps(hs, 6))
        finally:
            hs.close()

    @pytest.mark.parametrize("kw", [
        dict(use_importance_sampling=False),
        dict(),  # pool
    ])
    def test_w4_fused_bitwise_identical(self, mesh, kw):
        rep = Trainer(cfg(steps_per_epoch=8, **kw), mesh=mesh)
        hs = Trainer(cfg(data_placement="host_stream", prefetch_depth=2,
                         fused_input=True, steps_per_epoch=8, **kw),
                     mesh=mesh)
        try:
            np.testing.assert_array_equal(steps(rep, 6), stream_steps(hs, 6))
        finally:
            hs.close()

    def test_w4_scoretable_runs(self, mesh):
        hs = Trainer(cfg(data_placement="host_stream", prefetch_depth=2,
                         sampler="scoretable", steps_per_epoch=8), mesh=mesh)
        try:
            losses = stream_steps(hs, 6)
            assert np.all(np.isfinite(losses)), losses
        finally:
            hs.close()

    def test_w4_depth3_uniform_identical(self, mesh):
        rep = Trainer(cfg(steps_per_epoch=8,
                          use_importance_sampling=False), mesh=mesh)
        hs = Trainer(cfg(data_placement="host_stream", prefetch_depth=3,
                         steps_per_epoch=8,
                         use_importance_sampling=False), mesh=mesh)
        try:
            np.testing.assert_array_equal(steps(rep, 6), stream_steps(hs, 6))
        finally:
            hs.close()
