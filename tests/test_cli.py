"""CLI + config tests: every TrainConfig field is a flag; dry-run executes
one real step (replacing the reference's edit-source config,
``pytorch_collab.py:21-33``)."""

import dataclasses
import json

import numpy as np
import pytest

from mercury_tpu.cli import main, parse_config
from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.distributed import host_worker_slice, process_info
from mercury_tpu.parallel.mesh import host_cpu_mesh


class TestParseConfig:
    def test_defaults_roundtrip(self):
        config, _ = parse_config([])
        assert config == TrainConfig()

    def test_every_field_has_a_flag(self):
        config, _ = parse_config(
            ["--model", "vgg11", "--world-size", "2", "--base-lr", "0.01",
             "--noniid", "false", "--steps-per-epoch", "7"]
        )
        assert config.model == "vgg11"
        assert config.world_size == 2
        assert config.base_lr == 0.01
        assert config.noniid is False
        assert config.steps_per_epoch == 7

    def test_lr_linear_scaling(self):
        # lr = base_lr × world_size (pytorch_collab.py:28)
        config, _ = parse_config(["--world-size", "8"])
        assert config.lr == pytest.approx(0.008)

    def test_run_name_encodes_config(self):
        config, _ = parse_config(["--model", "resnet50", "--seed", "7"])
        name = config.run_name()
        assert "resnet50" in name and "seed7" in name

    def test_print_config_json(self, capsys):
        rc = main(["--print-config"])
        assert rc == 0
        data = json.loads(capsys.readouterr().out)
        assert set(data) == {f.name for f in dataclasses.fields(TrainConfig)}


class TestDryRun:
    def test_dry_run_executes_one_step(self, capsys):
        rc = main([
            "--model", "smallcnn", "--dataset", "synthetic",
            "--world-size", "8", "--batch-size", "4",
            "--presample-batches", "2", "--compute-dtype", "float32",
            "--dry-run",
        ])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()
        metrics = json.loads(out[-1])
        assert np.isfinite(metrics["train/loss"])


class TestDistributedHelpers:
    def test_process_info_single_host(self):
        idx, count = process_info()
        assert idx == 0 and count == 1

    def test_host_worker_slice_covers_all_on_single_host(self):
        mesh = host_cpu_mesh(8)
        workers = host_worker_slice(mesh)
        np.testing.assert_array_equal(workers, np.arange(8))
