"""Worker process for tests/test_distributed.py.

Forms one JAX distributed CPU cluster of ``NPROC`` processes × 4 virtual
devices, builds the global mesh, and runs cross-process collectives:

1. a psum of (process_index + 1) over all 8 devices — proves the collective
   crosses the process boundary (result 12 = 4·1 + 4·2, not 4 or 8);
2. a shard_map gradient-allreduce shaped like the train step's grad pmean,
   with per-device distinct contributions;
3. host_worker_slice — each host must own exactly its 4 mesh rows.

Prints one ``OK <psum> <pmean> <rows>`` line on success; any assertion or
hang is the test's failure signal.
"""

import os
import sys

# --solo: 1-process reference/elastic arm (8 virtual devices — the whole
# cluster in one process); workers get 4 each.
_SOLO = len(sys.argv) > 1 and sys.argv[1] == "--solo"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={8 if _SOLO else 4}"
).strip()
# Keep the remote-TPU plugin (sitecustomize) from claiming the backend.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
from mercury_tpu.platform import select_cpu_if_requested  # noqa: E402

select_cpu_if_requested()

import jax  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from mercury_tpu.compat import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

NPROC = 2


def main(port: str, pid: int) -> None:
    from mercury_tpu.parallel import distributed

    distributed.initialize(f"127.0.0.1:{port}", NPROC, pid)
    assert jax.process_count() == NPROC, jax.process_count()
    assert jax.local_device_count() == 4
    assert jax.device_count() == NPROC * 4

    me, n = distributed.process_info()
    assert (me, n) == (pid, NPROC)

    mesh = distributed.global_mesh()

    # 1. psum of per-process values: every device contributes
    #    (its process_index + 1) → 4·1 + 4·2 = 12.
    def contrib():
        return lax.psum(
            jnp.float32(jax.process_index() + 1), "data"
        )

    total = shard_map(contrib, mesh=mesh, in_specs=(), out_specs=P())
    try:
        psum_val = float(jax.jit(total)())
    except Exception as e:  # pragma: no cover - backend-dependent
        # Some jaxlib CPU builds can FORM a multiprocess cluster but not
        # EXECUTE cross-process collectives ("Multiprocess computations
        # aren't implemented on the CPU backend"). That is an environment
        # limitation, not a bug in parallel/distributed.py — surface it as
        # an explicit skip marker for the parent test, matched narrowly so
        # any other failure still fails loudly.
        if "Multiprocess computations aren't implemented" in str(e):
            print(
                "SKIP: jax CPU backend cannot execute cross-process "
                f"collectives in this build ({type(e).__name__})",
                flush=True,
            )
            return
        raise
    assert psum_val == 12.0, psum_val

    # 2. grad-allreduce shape: each worker row holds a distinct value;
    #    pmean must see all 8 rows across both processes. The [W, 1] input
    #    is assembled as a global array from per-host shards — the
    #    multi-controller version of the train step's sharded sampler state.
    rows = np.arange(NPROC * 4, dtype=np.float32).reshape(-1, 1)
    local_rows = rows[me * 4:(me + 1) * 4]
    garr = jax.make_array_from_process_local_data(
        jax.NamedSharding(mesh, P("data")), local_rows
    )

    def mean_fn(x):
        return lax.pmean(x[0, 0], "data")

    pmean = shard_map(mean_fn, mesh=mesh, in_specs=(P("data"),), out_specs=P())
    pmean_val = float(jax.jit(pmean)(garr))
    assert pmean_val == float(rows.mean()), pmean_val

    # 3. host_worker_slice: this host's 4 contiguous mesh positions.
    mine = distributed.host_worker_slice(mesh)
    assert mine.shape == (4,), mine

    # 4. A real Mercury train step, multi-controller: Trainer on the global
    #    8-device mesh (globalize_state/globalize_dataset re-place the
    #    host-created state), two fused steps + an eval — the loss is a
    #    replicated global scalar, identical on both processes by
    #    construction (same program, same global arrays).
    from mercury_tpu.config import TrainConfig
    from mercury_tpu.train.trainer import Trainer

    cfg = TrainConfig(
        model="smallcnn", dataset="synthetic", world_size=NPROC * 4,
        batch_size=4, presample_batches=2, steps_per_epoch=2, num_epochs=1,
        eval_every=0, log_every=0, compute_dtype="float32", seed=0,
    )
    trainer = Trainer(cfg, mesh=mesh)
    losses = []
    for _ in range(2):
        trainer.state, metrics = trainer.train_step(
            trainer.state, trainer.dataset.x_train, trainer.dataset.y_train,
            trainer.dataset.shard_indices,
        )
        losses.append(float(metrics["train/loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert int(trainer.state.step) == 2
    ev = trainer.evaluate(include_train=False)
    assert np.isfinite(ev["test/eval_loss"]), ev

    # 5. Checkpoint roundtrip across processes: the save all-gathers the
    #    cross-process-sharded sampler state (collective) and only process
    #    0 writes; restore re-globalizes and must land on the same step.
    ckpt_dir = os.environ["MERCURY_TEST_CKPT_DIR"]
    trainer.save(ckpt_dir)
    restored_step = trainer.restore(ckpt_dir)
    assert restored_step == 2, restored_step
    trainer.state, metrics = trainer.train_step(
        trainer.state, trainer.dataset.x_train, trainer.dataset.y_train,
        trainer.dataset.shard_indices,
    )
    post = float(metrics["train/loss"])
    assert np.isfinite(post), post

    # 6. ZeRO-1 multi-controller: globalize_state places the chunk-sharded
    #    optimizer state P("data") across processes; one step must run.
    trainer_z = Trainer(cfg.replace(zero_sharding=True), mesh=mesh)
    trainer_z.state, mz = trainer_z.train_step(
        trainer_z.state, trainer_z.dataset.x_train,
        trainer_z.dataset.y_train, trainer_z.dataset.shard_indices,
    )
    zloss = float(mz["train/loss"])
    assert np.isfinite(zloss), zloss

    # 7. Sharded data placement, multi-controller: each host materializes
    #    and transfers ONLY its own workers' shard rows — this process's
    #    addressable train-step data must be well under the full dataset —
    #    and the loss must equal the replicated-placement run bit-for-bit
    #    (same bytes, same program).
    trainer_s = Trainer(cfg.replace(data_placement="sharded"), mesh=mesh)
    local_bytes = sum(s.data.nbytes
                      for s in trainer_s._step_x.addressable_shards)
    full_bytes = np.asarray(trainer_s.dataset.x_train).nbytes
    assert local_bytes < 0.75 * full_bytes, (local_bytes, full_bytes)
    sl = None
    for _ in range(2):
        trainer_s.state, ms = trainer_s.train_step(
            trainer_s.state, trainer_s._step_x, trainer_s._step_y,
            trainer_s.dataset.shard_indices,
        )
        sl = float(ms["train/loss"])
    assert sl == losses[-1], (sl, losses[-1])

    # 8. dp×tp multi-controller: 4-way data × 2-way tensor parallelism
    #    over the same 2-process cluster. globalize_state places the
    #    params in the committed Megatron layout (params_sharding) and the
    #    optimizer init runs SPMD on the placed params — the fused IS step
    #    then runs with every transformer matmul TP-sharded ACROSS the
    #    process boundary (VERDICT round-2 item 6).
    cfg_tp = TrainConfig(
        model="transformer", dataset="synthetic_seq", augmentation="none",
        world_size=4, tensor_parallel=2, batch_size=4, presample_batches=2,
        steps_per_epoch=2, num_epochs=1, eval_every=0, log_every=0,
        compute_dtype="float32", seed=0,
    )
    trainer_tp = Trainer(cfg_tp)  # builds the global dp×tp mesh itself
    assert trainer_tp.mesh.shape == {"data": 4, "model": 2}
    # The Megatron split must be real on-device: a model-axis-sharded leaf's
    # per-device shard holds half the parameter.
    def model_split(l):
        return any(
            ax == "model" or (isinstance(ax, tuple) and "model" in ax)
            for ax in l.sharding.spec if ax is not None
        )

    tp_leaf = next(
        l for l in jax.tree_util.tree_leaves(trainer_tp.state.params)
        if model_split(l)
    )
    shard_bytes = tp_leaf.addressable_shards[0].data.nbytes
    assert shard_bytes * 2 == tp_leaf.nbytes, (shard_bytes, tp_leaf.nbytes)
    tl = None
    for _ in range(2):
        trainer_tp.state, mt = trainer_tp.train_step(
            trainer_tp.state, trainer_tp.dataset.x_train,
            trainer_tp.dataset.y_train, trainer_tp.dataset.shard_indices,
        )
        tl = float(mt["train/loss"])
    assert np.isfinite(tl), tl
    # The out-shardings pin must hold across the process boundary too.
    leaf_after = next(
        l for l in jax.tree_util.tree_leaves(trainer_tp.state.params)
        if model_split(l)
    )
    assert leaf_after.addressable_shards[0].data.nbytes * 2 == leaf_after.nbytes

    # 9. Elastic W→W′ on the SAME 2-process cluster (round-4: the
    #    multi-controller arm the round-3 review flagged as missing —
    #    elastic exists for preemption, which only happens multi-host).
    #    Train 8-way on the full cluster mesh, checkpoint, rebuild 4-way
    #    on a cross-process sub-mesh (2 devices from EACH host), restore
    #    elastically: params/moments transfer bit-exactly, the EMA warm
    #    start broadcasts, and the resumed 4-way step runs. The reference
    #    hangs forever on any topology change (pytorch_collab.py:291-292).
    import collections

    from jax.sharding import Mesh

    eck = os.path.join(ckpt_dir, "elastic")
    tr_e = Trainer(cfg.replace(checkpoint_dir=eck), mesh=mesh)
    for _ in range(2):
        tr_e.state, _ = tr_e.train_step(
            tr_e.state, tr_e.dataset.x_train, tr_e.dataset.y_train,
            tr_e.dataset.shard_indices,
        )
    tr_e.save()
    want_p = [np.asarray(l)
              for l in jax.tree_util.tree_leaves(tr_e.state.params)]
    want_o = [np.asarray(l)
              for l in jax.tree_util.tree_leaves(tr_e.state.opt_state)]

    by_proc = collections.defaultdict(list)
    for d in jax.devices():
        by_proc[d.process_index].append(d)
    sub = [d for p in sorted(by_proc)
           for d in sorted(by_proc[p], key=lambda d: d.id)[:2]]
    sub_mesh = Mesh(np.array(sub), ("data",))
    tr_e4 = Trainer(cfg.replace(world_size=4, checkpoint_dir=eck),
                    mesh=sub_mesh)
    estep = tr_e4.restore_elastic()
    assert estep == 2, estep
    for a, b in zip(want_p,
                    jax.tree_util.tree_leaves(tr_e4.state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    for a, b in zip(want_o,
                    jax.tree_util.tree_leaves(tr_e4.state.opt_state)):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert tr_e4.state.ema.value.shape == (4,)
    tr_e4.state, me4 = tr_e4.train_step(
        tr_e4.state, tr_e4.dataset.x_train, tr_e4.dataset.y_train,
        tr_e4.dataset.shard_indices,
    )
    el = float(me4["train/loss"])
    assert np.isfinite(el), el
    assert int(tr_e4.state.step) == 3

    # 10. host_stream, multi-controller: stream_shard_mode auto→"local" —
    #     each process's prefetch pipeline gathers ONLY its own workers'
    #     rows and device_puts them to its addressable shards; the global
    #     streamed batch assembles from per-host slabs. The pool sampler's
    #     lookahead replays the replicated RNG chain, so the streamed
    #     trajectory must equal section 4's replicated one bit-for-bit
    #     (and test_distributed.py checks it against a 1-process run too).
    hs_ckpt = os.path.join(ckpt_dir, "hs")
    tr_hs = Trainer(cfg.replace(data_placement="host_stream",
                                prefetch_depth=2, checkpoint_dir=hs_ckpt),
                    mesh=mesh)
    assert tr_hs._stream_local_workers is not None
    assert tr_hs._stream_local_workers.tolist() == mine.tolist()
    hs_losses = [float(tr_hs._host_stream_step()["train/loss"])
                 for _ in range(2)]
    assert hs_losses == losses, (hs_losses, losses)
    hl = hs_losses[-1]
    tr_hs.save()
    tr_hs.close()

    # 11. host_stream scoretable, checkpointed mid-epoch: the score table
    #     and cursors ride the checkpoint (stream_checkpoint_cursor);
    #     test_distributed.py hands this directory to a SOLO 1-process run
    #     that restores it elastically W=8 → W=4 — the 2→1-process world
    #     change — and checks the streamed-state carry.
    sc_ckpt = os.path.join(ckpt_dir, "hs_sc")
    tr_sc = Trainer(cfg.replace(data_placement="host_stream",
                                prefetch_depth=2, sampler="scoretable",
                                checkpoint_dir=sc_ckpt),
                    mesh=mesh)
    sc_losses = [float(tr_sc._host_stream_step()["train/loss"])
                 for _ in range(2)]
    assert all(np.isfinite(l) for l in sc_losses), sc_losses
    scl = sc_losses[-1]
    tr_sc.save()
    tr_sc.close()

    # Full precision (hex) so the cross-process comparison is bit-for-bit.
    print(f"OK {psum_val} {pmean_val} {mine.tolist()} "
          f"loss={losses[-1].hex()} post={post.hex()} zero={zloss.hex()} "
          f"sharded={sl.hex()} sharded_frac={local_bytes/full_bytes:.3f} "
          f"tp={tl.hex()} elastic={el.hex()} "
          f"hs={hl.hex()} sc={scl.hex()}",
          flush=True)


def solo(ckpt_dir: str) -> None:
    """1-process arm: (a) the same 8-worker host_stream pool config on 8
    local virtual devices — its trajectory must match the 2-process
    cluster's bit-for-bit (the multi-controller split is a pure dataflow
    change); (b) elastic restore of the cluster's mid-epoch host_stream
    checkpoints into ONE process at W=4 — the 2→1-process world change —
    asserting the stream cursor and the score table survive."""
    import numpy as np
    from jax.sharding import Mesh

    from mercury_tpu.config import TrainConfig
    from mercury_tpu.train.trainer import Trainer

    assert jax.local_device_count() == 8
    mesh = Mesh(np.array(jax.devices()), ("data",))
    cfg = TrainConfig(
        model="smallcnn", dataset="synthetic", world_size=8,
        batch_size=4, presample_batches=2, steps_per_epoch=2, num_epochs=1,
        eval_every=0, log_every=0, compute_dtype="float32", seed=0,
    )
    tr = Trainer(cfg.replace(data_placement="host_stream",
                             prefetch_depth=2), mesh=mesh)
    hs_losses = [float(tr._host_stream_step()["train/loss"])
                 for _ in range(2)]
    tr.close()
    print(f"SOLO hs={hs_losses[-1].hex()}", flush=True)

    from mercury_tpu.train.elastic import (
        _shard_index_matrix,
        probe_checkpoint,
    )

    mesh4 = Mesh(np.array(jax.devices()[:4]), ("data",))
    # Pool arm: the shard-stream cursor carries as an epoch fraction — the
    # restored cursor sits strictly past a fresh trainer's primed one.
    tr_p = Trainer(cfg.replace(world_size=4, data_placement="host_stream",
                               prefetch_depth=2,
                               checkpoint_dir=os.path.join(ckpt_dir, "hs")),
                   mesh=mesh4)
    fresh = np.asarray(tr_p.state.stream.cursor).copy()
    assert tr_p.restore_elastic() == 2
    after = np.asarray(tr_p.state.stream.cursor)
    assert np.all(after > fresh), (after, fresh)
    lp = float(tr_p._host_stream_step()["train/loss"])
    assert np.isfinite(lp), lp
    tr_p.close()

    # Scoretable arm: per-sample scores repartition by new worker
    # ownership — every sample the 8-way run owned keeps its learned
    # score bit-exactly under the 4-way index matrix.
    sc_dir = os.path.join(ckpt_dir, "hs_sc")
    raw, _ = probe_checkpoint(sc_dir, strict=True)
    tr_s = Trainer(cfg.replace(world_size=4, data_placement="host_stream",
                               prefetch_depth=2, sampler="scoretable",
                               checkpoint_dir=sc_dir),
                   mesh=mesh4)
    assert tr_s.restore_elastic() == 2
    old_scores = np.asarray(raw["scoretable"]["scores"], np.float32)
    ema_val = float(np.mean(np.asarray(raw["ema"]["value"])))
    old_sidx = _shard_index_matrix(tr_s, 8)
    new_sidx = _shard_index_matrix(tr_s, 4)
    assert old_sidx.shape == old_scores.shape, (old_sidx.shape,
                                                old_scores.shape)
    n = int(np.asarray(tr_s.dataset.y_train).size)
    want = np.full((n,), ema_val, np.float32)
    want[old_sidx.reshape(-1)] = old_scores.reshape(-1)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(tr_s.state.scoretable.scores)),
        want[new_sidx],
    )
    ls = float(tr_s._host_stream_step()["train/loss"])
    assert np.isfinite(ls), ls
    tr_s.close()
    print("SOLO elastic_ok", flush=True)


if __name__ == "__main__":
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    if _SOLO:
        solo(sys.argv[2])
    else:
        main(sys.argv[1], int(sys.argv[2]))
