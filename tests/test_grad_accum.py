"""Gradient accumulation (``config.grad_accum_steps``).

Beyond-parity training staple: ``optax.MultiSteps`` accumulates the mean
gradient over A microsteps and applies the parameter update on every A-th
— effective batch A×batch_size without the activation memory. Pins (1) the
accumulated update equals the update from the mean gradient, (2) params
freeze between update boundaries in the live Mercury step, (3) training
still learns end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.state import make_optimizer
from mercury_tpu.train.trainer import Trainer


def test_accumulated_update_equals_mean_gradient_update():
    params = {"w": jnp.arange(4.0)}
    g1 = {"w": jnp.array([1.0, 2.0, 3.0, 4.0])}
    g2 = {"w": jnp.array([3.0, 2.0, 1.0, 0.0])}
    gmean = jax.tree.map(lambda a, b: (a + b) / 2, g1, g2)

    acc = make_optimizer("sgd", 0.1, total_steps=100, grad_accum_steps=2)
    state = acc.init(params)
    p = params
    for g in (g1, g2):
        updates, state = acc.update(g, state, p)
        p = jax.tree.map(lambda a, u: a + u, p, updates)

    ref = make_optimizer("sgd", 0.1, total_steps=100)
    rstate = ref.init(params)
    updates, _ = ref.update(gmean, rstate, params)
    p_ref = jax.tree.map(lambda a, u: a + u, params, updates)

    np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(p_ref["w"]),
                               rtol=1e-6)


def test_params_freeze_between_update_boundaries():
    cfg = TrainConfig(
        model="smallcnn", dataset="synthetic", world_size=4, batch_size=4,
        presample_batches=2, steps_per_epoch=4, num_epochs=1,
        grad_accum_steps=2, eval_every=0, log_every=0,
        compute_dtype="float32", seed=0,
    )
    tr = Trainer(cfg, mesh=host_cpu_mesh(4))
    p0 = jax.tree.map(np.asarray, tr.state.params)
    tr.state, _ = tr.train_step(tr.state, tr.dataset.x_train,
                                tr.dataset.y_train, tr.dataset.shard_indices)
    p1 = jax.tree.map(np.asarray, tr.state.params)
    # Microstep 1 of 2: gradient accumulated, no parameter update yet.
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
        np.testing.assert_array_equal(a, b)
    tr.state, _ = tr.train_step(tr.state, tr.dataset.x_train,
                                tr.dataset.y_train, tr.dataset.shard_indices)
    p2 = jax.tree.map(np.asarray, tr.state.params)
    # Boundary: the accumulated update applies.
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2))
    )
    assert changed, "params did not update at the accumulation boundary"


def test_training_learns_with_accumulation():
    cfg = TrainConfig(
        model="smallcnn", dataset="synthetic", world_size=4, batch_size=8,
        presample_batches=2, steps_per_epoch=100, num_epochs=1,
        base_lr=0.003, grad_accum_steps=2, eval_every=0, log_every=0,
        compute_dtype="float32", seed=0,
    )
    tr = Trainer(cfg, mesh=host_cpu_mesh(4))
    losses = []
    for _ in range(100):
        tr.state, m = tr.train_step(tr.state, tr.dataset.x_train,
                                    tr.dataset.y_train,
                                    tr.dataset.shard_indices)
        losses.append(float(m["train/loss"]))
    assert all(np.isfinite(l) for l in losses)
    # 100 microsteps = 50 updates; the synthetic task's loss must be well
    # on its way down.
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.8
