"""Cross-feature composition matrix.

Each knob is tested in depth in its own file; this matrix guards the
*combinations* — a regression in how two features interact (e.g. a state
field one path forgets to thread) surfaces here as a crash or NaN within
a few steps.
"""

import numpy as np
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.trainer import Trainer

pytestmark = pytest.mark.slow  # parallelism-matrix compile cost blows the tier-1 budget

W = 4

COMBOS = {
    "pipelined+zero": dict(pipelined_scoring=True, zero_sharding=True),
    "pipelined+int8": dict(pipelined_scoring=True, grad_compression="int8"),
    "groupwise+zero": dict(sampler="groupwise", zero_sharding=True),
    "groupwise+accum": dict(sampler="groupwise", grad_accum_steps=2),
    "int8+accum": dict(grad_compression="int8", grad_accum_steps=2),
    "zero+accum+warmup": dict(zero_sharding=True, grad_accum_steps=2,
                              warmup_steps=4),
    "stochastic+zero": dict(grad_compression="stochastic",
                            zero_sharding=True),
    "uniform+zero": dict(use_importance_sampling=False, zero_sharding=True),
    "scan+zero": dict(scan_steps=2, zero_sharding=True),
    "scan+int8+pipelined": dict(scan_steps=2, grad_compression="int8",
                                pipelined_scoring=True),
    # Round 3: score-refresh cadence through the rest of the matrix — the
    # CachedPool state field must thread through every path variant.
    "cadence+zero": dict(score_refresh_every=2, zero_sharding=True),
    "cadence+int8": dict(score_refresh_every=2, grad_compression="int8"),
    "cadence+accum": dict(score_refresh_every=2, grad_accum_steps=2),
    "cadence+sharded-data": dict(score_refresh_every=2,
                                 data_placement="sharded"),
    "cadence+scan+zero": dict(score_refresh_every=2, scan_steps=2,
                              zero_sharding=True),
    # Round 3: int8 x ZeRO (both wire phases compressed) under scan.
    "int8+zero+scan": dict(grad_compression="int8", zero_sharding=True,
                           scan_steps=2),
    # Round 4: FSDP (fsdp_parallel — params GSPMD-sharded over a second
    # mesh axis) through the rest of the matrix. world_size=2×fsdp=2 on
    # the 8-device pool; the Trainer builds its own dp×fsdp mesh.
    "fsdp+cadence": dict(fsdp_parallel=2, world_size=2,
                         score_refresh_every=2),
    "fsdp+int8": dict(fsdp_parallel=2, world_size=2,
                      grad_compression="int8"),
    "fsdp+scan": dict(fsdp_parallel=2, world_size=2, scan_steps=2),
    "fsdp+accum": dict(fsdp_parallel=2, world_size=2, grad_accum_steps=2),
    "fsdp+pipelined": dict(fsdp_parallel=2, world_size=2,
                           pipelined_scoring=True),
    "fsdp+groupwise": dict(fsdp_parallel=2, world_size=2,
                           sampler="groupwise"),
}


@pytest.mark.parametrize("name", sorted(COMBOS))
def test_combo_trains_finite(name):
    kw = dict(
        model="smallcnn", dataset="synthetic", world_size=W, batch_size=4,
        presample_batches=2, steps_per_epoch=6, num_epochs=1,
        eval_every=0, log_every=0, compute_dtype="float32", seed=0,
    )
    kw.update(COMBOS[name])  # combo overrides win (fsdp rows set world_size)
    cfg = TrainConfig(**kw)
    tr = Trainer(cfg, mesh=(None if cfg.fsdp_parallel > 1
                            else host_cpu_mesh(W)))
    step_fn = tr.train_step_many or tr.train_step
    steps = 6 // max(cfg.scan_steps, 1)
    for _ in range(steps):
        # _step_x/_step_y: correct for both data placements (they alias
        # the dataset arrays under "replicated").
        tr.state, m = step_fn(tr.state, tr._step_x, tr._step_y,
                              tr.dataset.shard_indices)
        loss = np.asarray(m["train/loss"])
        assert np.all(np.isfinite(loss)), (name, loss)
    assert int(tr.state.step) == 6
