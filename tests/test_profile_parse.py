"""Offline device-time attribution (obs/profile_parse.py): scope
bucketing against the committed anonymized capture fixture, the
accounting identity (every device microsecond lands in a bucket),
H2D-overlap and idle interval math, the protobuf wire-format xplane
reader against a hand-encoded capture, capture discovery, and the CLI.

The module is deliberately jax-free — one test pins that by running the
CLI in a subprocess and asserting jax never entered sys.modules.
"""

import gzip
import json
import os
import subprocess
import sys

import pytest

from mercury_tpu.obs.profile_parse import (
    BREAKDOWN_SCHEMA,
    SCOPES,
    UNATTRIBUTED,
    attribute_device_time,
    discover_capture_files,
    load_chrome_events,
    load_events,
    load_xplane_events,
    main,
    parse_profile,
    scope_frac_metrics,
    write_breakdown,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "profile_trace.json")


def meta_events(pid=1, pname="/device:TPU:0", lanes=((3, "XLA Ops"),)):
    evs = [{"ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": pname}}]
    for tid, tname in lanes:
        evs.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "args": {"name": tname}})
    return evs


def op(name, ts, dur, pid=1, tid=3):
    return {"ph": "X", "name": name, "ts": ts, "dur": dur,
            "pid": pid, "tid": tid, "cat": "tpu_op"}


class TestFixtureAttribution:
    def test_fixture_meets_attribution_floor(self):
        bd = parse_profile(FIXTURE)
        assert bd["schema"] == BREAKDOWN_SCHEMA
        # The acceptance bar: >= 95% of device-lane time named (the
        # explicit unattributed bucket counts as named).
        assert bd["attributed_frac"] >= 0.95
        fracs = {k: v["frac"] for k, v in bd["scopes"].items()}
        assert set(fracs) == set(SCOPES) | {UNATTRIBUTED}
        assert sum(fracs.values()) == pytest.approx(1.0)
        # Scoring dominates the synthetic step, as on the real chip.
        assert max(fracs, key=fracs.get) == "mercury_scoring"

    def test_container_lanes_not_double_counted(self):
        bd = parse_profile(FIXTURE)
        # 3 step windows x 8 XLA Ops events; the "Steps" and "XLA
        # Modules" container lanes span the same time and must be
        # excluded from the op-lane attribution.
        assert bd["counts"]["device_events"] == 24
        assert bd["counts"]["lane"] == "xla_ops"

    def test_h2d_and_idle_measured(self):
        bd = parse_profile(FIXTURE)
        assert bd["counts"]["h2d_events"] == 6
        assert 0.0 < bd["h2d"]["overlap_frac"] <= 1.0
        assert 0.0 < bd["idle"]["idle_frac"] < 1.0


class TestAttributionMath:
    def test_accounting_identity_with_unknown_ops(self):
        events = meta_events() + [
            op("fusion.1 mercury_scoring/dot", 0, 100),
            op("all-reduce mercury_grad_sync", 100, 50),
            op("some-unknown-fusion.7", 150, 25),
        ]
        bd = attribute_device_time(events)
        assert bd["total_device_time_us"] == pytest.approx(175.0)
        assert bd["attributed_frac"] == pytest.approx(1.0)
        assert bd["scopes"]["mercury_scoring"]["frac"] == pytest.approx(
            100 / 175)
        assert bd["scopes"][UNATTRIBUTED]["time_us"] == pytest.approx(25.0)

    def test_scope_match_priority_first_wins(self):
        # A nested scope name attributes to the FIRST matching anchor in
        # SCOPES order, not to both.
        events = meta_events() + [
            op("mercury_scoring/mercury_augmentation/crop", 0, 10)]
        bd = attribute_device_time(events)
        assert bd["scopes"]["mercury_scoring"]["time_us"] == 10.0
        assert bd["scopes"]["mercury_augmentation"]["time_us"] == 0.0

    def test_scope_in_args_counts(self):
        # jax exports sometimes put the name stack in args, not name.
        events = meta_events() + [
            {"ph": "X", "name": "fusion.3", "ts": 0, "dur": 10, "pid": 1,
             "tid": 3, "args": {"tf_op": "mercury_grad_sync/psum"}}]
        bd = attribute_device_time(events)
        assert bd["scopes"]["mercury_grad_sync"]["time_us"] == 10.0

    def test_host_lanes_ignored(self):
        events = meta_events() + [
            {"ph": "M", "name": "process_name", "pid": 9,
             "args": {"name": "python"}},
            op("mercury_scoring/x", 0, 10),
            op("mercury_scoring/host_side", 0, 999, pid=9, tid=1),
        ]
        bd = attribute_device_time(events)
        assert bd["total_device_time_us"] == pytest.approx(10.0)

    def test_busiest_lane_fallback_without_xla_ops_tag(self):
        # No "XLA Ops" thread name anywhere: fall back to the busiest
        # device lane, deterministically.
        events = meta_events(lanes=((1, "lane a"), (2, "lane b"))) + [
            op("mercury_scoring/a", 0, 10, tid=1),
            op("mercury_scoring/b", 0, 100, tid=2),
        ]
        bd = attribute_device_time(events)
        assert bd["counts"]["lane"] == "busiest_device_lane"
        assert bd["total_device_time_us"] == pytest.approx(100.0)

    def test_h2d_overlap_intervals(self):
        # Compute [0,100]; copies [50,70] (hidden) and [200,210]
        # (exposed): overlap = 20 of 30 total copy time.
        events = meta_events(lanes=((3, "XLA Ops"),
                                    (4, "XLA Async Ops #memcpy"))) + [
            op("mercury_scoring/x", 0, 100),
            op("MemcpyH2D.0", 50, 20, tid=4),
            op("MemcpyH2D.1", 200, 10, tid=4),
        ]
        bd = attribute_device_time(events)
        assert bd["h2d"]["total_us"] == pytest.approx(30.0)
        assert bd["h2d"]["overlap_us"] == pytest.approx(20.0)
        assert bd["h2d"]["overlap_frac"] == pytest.approx(20 / 30)

    def test_idle_gaps_over_span(self):
        # Busy [0,10] and [40,50] over span [0,50]: 30/50 idle.
        events = meta_events() + [
            op("mercury_scoring/a", 0, 10),
            op("mercury_optimizer/b", 40, 10),
        ]
        bd = attribute_device_time(events)
        assert bd["idle"]["span_us"] == pytest.approx(50.0)
        assert bd["idle"]["idle_us"] == pytest.approx(30.0)
        assert bd["idle"]["idle_frac"] == pytest.approx(0.6)

    def test_empty_capture_is_all_zeros_not_crash(self):
        bd = attribute_device_time([])
        assert bd["total_device_time_us"] == 0.0
        assert bd["attributed_frac"] == 0.0
        assert bd["counts"]["lane"] == "none"


class TestScopeFracMetrics:
    def test_registered_keys_only(self):
        from mercury_tpu.obs.registry import METRIC_KEYS

        bd = parse_profile(FIXTURE)
        metrics = scope_frac_metrics(bd)
        assert set(metrics) <= set(METRIC_KEYS)
        assert metrics["prof/scope_frac/mercury_scoring"] > 0.0
        assert "prof/h2d_overlap_frac" in metrics
        assert "prof/idle_frac" in metrics


def encode_varint(v):
    out = b""
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def field(num, payload):
    if isinstance(payload, int):
        return encode_varint(num << 3) + encode_varint(payload)
    return encode_varint((num << 3) | 2) + encode_varint(len(payload)) \
        + payload


def encode_xplane_capture():
    """Hand-encode a one-plane xplane.pb on the profiler's stable field
    numbers: enough for the wire reader to reconstruct two named ops."""
    ev1 = field(1, 1) + field(2, 0) + field(3, 100_000_000)  # 100 us
    ev2 = field(1, 2) + field(2, 100_000_000) + field(3, 50_000_000)
    line = (field(2, b"XLA Ops") + field(3, 1_000_000)
            + field(4, ev1) + field(4, ev2))
    md1 = field(1, 1) + field(2, field(1, 1)
                                + field(2, b"mercury_scoring/dot.1"))
    md2 = field(1, 2) + field(2, field(1, 2)
                                + field(2, b"loop_fusion.9"))
    plane = (field(2, b"/device:TPU:0") + field(3, line)
             + field(4, md1) + field(4, md2))
    return field(1, plane)  # XSpace.planes


class TestXplaneWireReader:
    def test_decode_and_attribute(self, tmp_path):
        path = str(tmp_path / "host0.xplane.pb")
        with open(path, "wb") as f:
            f.write(encode_xplane_capture())
        events = load_xplane_events(path)
        assert [e["name"] for e in events] == [
            "mercury_scoring/dot.1", "loop_fusion.9"]
        # ps -> us conversion, line timestamp offset applied.
        assert events[0]["dur"] == pytest.approx(100.0)
        assert events[0]["ts"] == pytest.approx(1000.0)
        bd = attribute_device_time(events)
        assert bd["scopes"]["mercury_scoring"]["frac"] == pytest.approx(
            100 / 150)
        assert bd["scopes"][UNATTRIBUTED]["frac"] == pytest.approx(50 / 150)
        assert bd["attributed_frac"] == pytest.approx(1.0)

    def test_display_name_fallback(self, tmp_path):
        line = field(11, b"XLA Ops") + field(3, 0)  # display_name only
        plane = field(2, b"/device:TPU:0") + field(3, line)
        path = str(tmp_path / "x.xplane.pb")
        with open(path, "wb") as f:
            f.write(field(1, plane))
        assert load_xplane_events(path) == []  # no events, but no crash


class TestLoadingAndDiscovery:
    def test_gzip_chrome_trace(self, tmp_path):
        doc = {"traceEvents": meta_events() + [op("mercury_scoring/x",
                                                  0, 10)]}
        path = str(tmp_path / "t.trace.json.gz")
        with gzip.open(path, "wt") as f:
            json.dump(doc, f)
        events = load_chrome_events(path)
        assert len(events) == 3
        bd = attribute_device_time(events)
        assert bd["total_device_time_us"] == pytest.approx(10.0)

    def test_bare_list_document(self, tmp_path):
        path = str(tmp_path / "trace.json")
        with open(path, "w") as f:
            json.dump([op("x", 0, 1)], f)
        assert len(load_chrome_events(path)) == 1

    def test_directory_discovery_prefers_chrome_and_newest(self, tmp_path):
        prof = tmp_path / "profile" / "plugins" / "profile" / "run1"
        prof.mkdir(parents=True)
        chrome = prof / "host0.trace.json.gz"
        with gzip.open(str(chrome), "wt") as f:
            json.dump({"traceEvents": []}, f)
        (prof / "host0.xplane.pb").write_bytes(encode_xplane_capture())
        found = discover_capture_files(str(tmp_path))
        assert found == [str(chrome)]  # chrome wins over xplane

    def test_load_events_from_directory(self, tmp_path):
        with open(str(tmp_path / "trace.json"), "w") as f:
            json.dump({"traceEvents": meta_events()
                       + [op("mercury_scoring/x", 0, 10)]}, f)
        events, source = load_events(str(tmp_path))
        assert len(events) == 3
        assert source.endswith("trace.json")

    def test_empty_directory_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_events(str(tmp_path))


class TestCli:
    def test_main_writes_breakdown(self, tmp_path, capsys):
        out = str(tmp_path / "bd.json")
        assert main([FIXTURE, "--out", out]) == 0
        bd = json.load(open(out))
        assert bd["schema"] == BREAKDOWN_SCHEMA
        assert bd["attributed_frac"] >= 0.95
        stdout = capsys.readouterr().out
        assert "mercury_scoring" in stdout

    def test_main_bad_capture_is_rc2(self, tmp_path, capsys):
        bad = str(tmp_path / "trace.json")
        with open(bad, "w") as f:
            f.write("{not json")
        assert main([bad, "--out", str(tmp_path / "o.json")]) == 2
        assert "error" in capsys.readouterr().err

    def test_write_breakdown_is_atomic_named(self, tmp_path):
        path = str(tmp_path / "sub" / "bd.json")
        write_breakdown({"schema": BREAKDOWN_SCHEMA}, path)
        assert json.load(open(path))["schema"] == BREAKDOWN_SCHEMA
        assert not os.path.exists(path + ".tmp")

    def test_cli_never_imports_jax(self, tmp_path):
        # The tentpole contract: offline attribution must run on a
        # jax-less analysis box.
        code = (
            "import sys\n"
            "from mercury_tpu.obs.profile_parse import main\n"
            f"rc = main([{FIXTURE!r}, '--out', "
            f"{str(tmp_path / 'bd.json')!r}])\n"
            "assert rc == 0, rc\n"
            "assert 'jax' not in sys.modules, 'jax was imported'\n"
        )
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120,
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))))
        assert r.returncode == 0, r.stderr
