"""Numerical cross-checks against PyTorch (CPU) — independent evidence
that the jittable Mercury math matches the reference's torch semantics
without translating its code.

Covers the three numerical contracts the algorithm rests on:
- per-sample CE ≡ ``F.cross_entropy(..., reduction='none')``
  (``pytorch_collab.py:102,133``)
- the IS reweighting ``mean(loss/(N·p))`` ≡ dividing torch losses by
  ``probs`` scaled by N (``:116,:137``)
- EMA smoothing ≡ the reference's ``EMAverage`` recurrence with
  first-update bootstrap (``util.py:200-217``)
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mercury_tpu.sampling.importance import (  # noqa: E402
    EMAState,
    ema_update,
    importance_probs,
    init_ema,
    per_sample_loss,
    reweighted_loss,
)


class TestTorchCrossCheck:
    def test_per_sample_ce_matches_torch(self):
        rng = np.random.default_rng(0)
        logits = rng.normal(size=(64, 10)).astype(np.float32)
        labels = rng.integers(0, 10, size=64)
        ours = np.asarray(per_sample_loss(jnp.asarray(logits), jnp.asarray(labels)))
        theirs = torch.nn.functional.cross_entropy(
            torch.from_numpy(logits), torch.from_numpy(labels), reduction="none"
        ).numpy()
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    def test_label_smoothing_matches_torch(self):
        rng = np.random.default_rng(1)
        logits = rng.normal(size=(32, 10)).astype(np.float32)
        labels = rng.integers(0, 10, size=32)
        ours = np.asarray(
            per_sample_loss(jnp.asarray(logits), jnp.asarray(labels),
                            label_smoothing=0.1)
        )
        theirs = torch.nn.functional.cross_entropy(
            torch.from_numpy(logits), torch.from_numpy(labels),
            reduction="none", label_smoothing=0.1,
        ).numpy()
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    def test_reweighted_estimator_matches_torch_expression(self):
        """losses/probs then mean — the literal torch expression at
        ``pytorch_collab.py:137`` with probs = p·N from ``:116``."""
        rng = np.random.default_rng(2)
        losses = rng.uniform(0.1, 3.0, size=32).astype(np.float32)
        pool_losses = rng.uniform(0.1, 3.0, size=320).astype(np.float32)
        probs_full = np.asarray(importance_probs(jnp.asarray(pool_losses),
                                                 jnp.asarray(0.5), 0.5))
        sel = rng.integers(0, 320, size=32)
        scaled = probs_full[sel] * 320.0
        ours = float(reweighted_loss(jnp.asarray(losses), jnp.asarray(scaled)))
        theirs = float(
            (torch.from_numpy(losses) / torch.from_numpy(scaled)).mean()
        )
        np.testing.assert_allclose(ours, theirs, rtol=1e-6)

    def test_ema_matches_reference_recurrence(self):
        """value₀ bootstraps; then ema ← α·ema + (1−α)·v (util.py:207-213)."""
        values = [2.0, 1.5, 1.0, 0.8]
        state = EMAState(value=jnp.zeros(()), count=jnp.zeros((), jnp.int32))
        for v in values:
            state = ema_update(state, jnp.asarray(v), alpha=0.9)
        expect = values[0]
        for v in values[1:]:
            expect = 0.9 * expect + 0.1 * v
        np.testing.assert_allclose(float(state.value), expect, rtol=1e-6)

    def test_categorical_draw_matches_torch_multinomial_distribution(self):
        """Same probs → same long-run draw frequencies as
        ``torch.multinomial(..., replacement=True)`` (``:114``)."""
        from mercury_tpu.sampling.importance import draw_with_replacement

        probs = np.asarray([0.05, 0.1, 0.15, 0.3, 0.4], np.float32)
        n = 40_000
        ours = np.asarray(
            draw_with_replacement(jax.random.key(0), jnp.asarray(probs), n)
        )
        g = torch.Generator().manual_seed(0)
        theirs = torch.multinomial(
            torch.from_numpy(probs), n, replacement=True, generator=g
        ).numpy()
        f_ours = np.bincount(ours, minlength=5) / n
        f_theirs = np.bincount(theirs, minlength=5) / n
        np.testing.assert_allclose(f_ours, probs, atol=0.01)
        np.testing.assert_allclose(f_theirs, probs, atol=0.01)
        np.testing.assert_allclose(f_ours, f_theirs, atol=0.015)
