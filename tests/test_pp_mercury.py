"""Mercury IS step on a pipelined model (train/pp_step.py): the staged
schedule must not change the algorithm — a 4-stage pipeline reproduces the
1-stage (dense-equivalent) run bit-for-bit in expectation (same RNG, same
draws), and the composed step learns."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from mercury_tpu.models import TransformerClassifier
from mercury_tpu.train.pp_step import create_pp_state, make_pp_mercury_step

pytestmark = pytest.mark.slow  # parallelism-matrix compile cost blows the tier-1 budget

T, F, C, D, L = 16, 8, 5, 32, 4


def _data(n=256, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(k1, (n, T, F), jnp.float32)
    y = jax.random.randint(k2, (n,), 0, C)
    return x, y


def _model(**kw):
    return TransformerClassifier(num_classes=C, d_model=D, num_heads=2,
                                 num_layers=L, max_len=T, **kw)


def _run(mesh, steps, batch=8, pool_batches=2, model=None):
    model = model if model is not None else _model()
    tx = optax.adam(1e-3)
    x, y = _data()
    state = create_pp_state(jax.random.key(0), model, tx, x[:1],
                            shard_len=len(x), mesh=mesh)
    step = make_pp_mercury_step(model, tx, mesh, batch_size=batch,
                                presample_batches=pool_batches,
                                num_microbatches=2)
    losses = []
    m = None
    for _ in range(steps):
        state, m = step(state, x, y)
        losses.append(float(m["train/loss"]))
    return state, losses, m


class TestPPMercury:
    def test_staged_matches_single_stage(self):
        """4 pipeline stages ≡ 1 stage (dense-equivalent): same RNG → same
        pool, same draws, same losses (fp32 reorder tolerance only)."""
        dense_mesh = Mesh(np.array(jax.devices()[:1]), ("pipe",))
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        _, dense_losses, _ = _run(dense_mesh, 3)
        _, pp_losses, _ = _run(pp_mesh, 3)
        np.testing.assert_allclose(pp_losses, dense_losses, rtol=1e-4)

    def test_block_params_stay_staged(self):
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        state, _, _ = _run(pp_mesh, 2)
        leaf = jax.tree_util.tree_leaves(state.stacked)[0]
        assert leaf.shape[0] == L
        assert leaf.addressable_shards[0].data.shape[0] == L // 4
        # Optimizer moments inherit the staging.
        mu_leaf = jax.tree_util.tree_leaves(state.opt_state[0].mu[0])[0]
        assert mu_leaf.addressable_shards[0].data.shape[0] == L // 4

    def test_learns(self):
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        _, losses, _ = _run(pp_mesh, 25, batch=16)
        assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses

    def test_microbatch_divisibility_rejected(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        with pytest.raises(ValueError, match="num_microbatches"):
            make_pp_mercury_step(_model(), optax.adam(1e-3), mesh,
                                 batch_size=9, num_microbatches=2)


class TestPPMercuryMoE:
    """Switch-MoE through the pipelined Mercury step (round 4 — closes the
    round-3 rejection at the old pp_step.py:101-111): the router's
    load-balancing aux flows out of the staged scan and into the
    reweighted objective with the same ``moe_aux_weight`` semantics as the
    fused data-parallel step."""

    def _moe_model(self):
        return _model(moe_experts=2, moe_capacity_factor=8.0)

    def test_moe_staged_matches_single_stage(self):
        """pp-mercury × MoE ≡ the dense-path (1-stage) MoE step: same RNG
        → same pool, same draws, same losses."""
        dense_mesh = Mesh(np.array(jax.devices()[:1]), ("pipe",))
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        _, dense_losses, md = _run(dense_mesh, 3, model=self._moe_model())
        _, pp_losses, mp = _run(pp_mesh, 3, model=self._moe_model())
        np.testing.assert_allclose(pp_losses, dense_losses, rtol=1e-4)
        np.testing.assert_allclose(float(mp["train/moe_aux"]),
                                   float(md["train/moe_aux"]), rtol=1e-4)

    def test_moe_aux_live_in_objective(self):
        """The aux term is nonzero (a top-1 router off perfect balance)
        and actually enters the gradient: training with aux weight 0 vs
        default diverges in params after a few steps."""
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        _, _, m = _run(pp_mesh, 2, model=self._moe_model())
        assert float(m["train/moe_aux"]) > 0.0

        x, y = _data()
        tx = optax.adam(1e-3)
        model = self._moe_model()
        outs = []
        for w in (0.0, 1.0):
            state = create_pp_state(jax.random.key(0), model, tx, x[:1],
                                    shard_len=len(x), mesh=pp_mesh)
            step = make_pp_mercury_step(model, tx, pp_mesh, batch_size=8,
                                        presample_batches=2,
                                        num_microbatches=2,
                                        moe_aux_weight=w)
            for _ in range(3):
                state, _ = step(state, x, y)
            outs.append(state)
        diffs = [float(jnp.max(jnp.abs(a - b))) for a, b in zip(
            jax.tree_util.tree_leaves(outs[0].stacked),
            jax.tree_util.tree_leaves(outs[1].stacked))]
        assert max(diffs) > 1e-6, "aux weight had no effect on training"

    def test_moe_learns(self):
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        _, losses, _ = _run(pp_mesh, 25, batch=16, model=self._moe_model())
        assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses
