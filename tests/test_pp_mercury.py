"""Mercury IS step on a pipelined model (train/pp_step.py): the staged
schedule must not change the algorithm — a 4-stage pipeline reproduces the
1-stage (dense-equivalent) run bit-for-bit in expectation (same RNG, same
draws), and the composed step learns."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from mercury_tpu.models import TransformerClassifier
from mercury_tpu.train.pp_step import create_pp_state, make_pp_mercury_step

T, F, C, D, L = 16, 8, 5, 32, 4


def _data(n=256, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(k1, (n, T, F), jnp.float32)
    y = jax.random.randint(k2, (n,), 0, C)
    return x, y


def _model():
    return TransformerClassifier(num_classes=C, d_model=D, num_heads=2,
                                 num_layers=L, max_len=T)


def _run(mesh, steps, batch=8, pool_batches=2):
    model = _model()
    tx = optax.adam(1e-3)
    x, y = _data()
    state = create_pp_state(jax.random.key(0), model, tx, x[:1],
                            shard_len=len(x), mesh=mesh)
    step = make_pp_mercury_step(model, tx, mesh, batch_size=batch,
                                presample_batches=pool_batches,
                                num_microbatches=2)
    losses = []
    for _ in range(steps):
        state, m = step(state, x, y)
        losses.append(float(m["train/loss"]))
    return state, losses


class TestPPMercury:
    def test_staged_matches_single_stage(self):
        """4 pipeline stages ≡ 1 stage (dense-equivalent): same RNG → same
        pool, same draws, same losses (fp32 reorder tolerance only)."""
        dense_mesh = Mesh(np.array(jax.devices()[:1]), ("pipe",))
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        _, dense_losses = _run(dense_mesh, 3)
        _, pp_losses = _run(pp_mesh, 3)
        np.testing.assert_allclose(pp_losses, dense_losses, rtol=1e-4)

    def test_block_params_stay_staged(self):
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        state, _ = _run(pp_mesh, 2)
        leaf = jax.tree_util.tree_leaves(state.stacked)[0]
        assert leaf.shape[0] == L
        assert leaf.addressable_shards[0].data.shape[0] == L // 4
        # Optimizer moments inherit the staging.
        mu_leaf = jax.tree_util.tree_leaves(state.opt_state[0].mu[0])[0]
        assert mu_leaf.addressable_shards[0].data.shape[0] == L // 4

    def test_learns(self):
        pp_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        _, losses = _run(pp_mesh, 25, batch=16)
        assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses

    def test_microbatch_divisibility_rejected(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        with pytest.raises(ValueError, match="num_microbatches"):
            make_pp_mercury_step(_model(), optax.adam(1e-3), mesh,
                                 batch_size=9, num_microbatches=2)
