"""Guard the driver contract in ``__graft_entry__.py``.

The driver compile-checks ``entry()`` single-chip and executes
``dryrun_multichip(N)`` on N virtual CPU devices; a regression there fails
the whole round silently, so pin both here (the conftest already provides
the 8-device CPU platform the driver uses).
"""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (32, 10)
    assert np.all(np.isfinite(np.asarray(out, np.float32)))


@pytest.mark.slow  # ~3 min of arm compiles; the 2-device run below covers
# every arm inside the tier-1 budget
def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2():
    # Smallest even mesh — exercises the guard that skips the dp×sp arm.
    graft.dryrun_multichip(2)
