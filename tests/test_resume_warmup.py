"""LR warmup (``config.warmup_steps``) and crash recovery
(``config.auto_resume``).

The reference has neither (cosine from step 0, ``pytorch_collab.py:62``;
no checkpointing at all — SURVEY.md §5). Warmup is pinned at the schedule
level; auto-resume is pinned as the real workflow: train, "crash", rebuild
the same Trainer, and confirm it continues from the checkpoint to the
original horizon with a bit-identical sampler trajectory.
"""

import jax
import numpy as np

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.state import make_optimizer
from mercury_tpu.train.trainer import Trainer

W = 4


def _cfg(**kw):
    base = dict(
        model="smallcnn", dataset="synthetic", world_size=W, batch_size=8,
        presample_batches=2, steps_per_epoch=10, num_epochs=1,
        eval_every=0, log_every=0, compute_dtype="float32", seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def test_warmup_schedule_shape():
    import optax

    # Probe the schedule through the optimizer's hyperparams indirectly:
    # rebuild the same schedule and check endpoints.
    lr = 0.01
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=lr, warmup_steps=10, decay_steps=100)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), lr, rtol=1e-6)
    assert float(sched(100)) < lr * 0.01

    # And that make_optimizer with warmup actually produces near-zero first
    # updates vs the no-warmup optimizer (sgd: update = -lr_t * grad).
    params = {"w": np.ones(4, np.float32)}
    grads = {"w": np.ones(4, np.float32)}
    warm = make_optimizer("sgd", lr, total_steps=100, warmup_steps=10)
    cold = make_optimizer("sgd", lr, total_steps=100)
    uw, _ = warm.update(grads, warm.init(params), params)
    uc, _ = cold.update(grads, cold.init(params), params)
    assert abs(float(uw["w"][0])) < abs(float(uc["w"][0])) * 0.2


def test_training_with_warmup_learns():
    cfg = _cfg(warmup_steps=20, steps_per_epoch=80)
    tr = Trainer(cfg, mesh=host_cpu_mesh(W))
    losses = []
    for _ in range(80):
        tr.state, m = tr.train_step(tr.state, tr.dataset.x_train,
                                    tr.dataset.y_train,
                                    tr.dataset.shard_indices)
        losses.append(float(m["train/loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


class TestAutoResume:
    def test_resume_continues_to_original_horizon(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = _cfg(checkpoint_dir=ckpt_dir, checkpoint_every=5,
                   auto_resume=True, steps_per_epoch=10)
        mesh = host_cpu_mesh(W)

        # Run 1: "crashes" after 6 steps (checkpoint exists at step 5).
        tr1 = Trainer(cfg, mesh=mesh)
        for _ in range(6):
            tr1.state, _ = tr1.train_step(
                tr1.state, tr1.dataset.x_train, tr1.dataset.y_train,
                tr1.dataset.shard_indices)
        tr1.save()  # simulate the cadence checkpoint at the crash point

        # Run 2: same config/script — must resume at 6 and stop at 10
        # (the original horizon), not train 10 more.
        tr2 = Trainer(cfg, mesh=mesh)
        assert int(tr2.state.step) == 6
        tr2.fit()
        assert int(tr2.state.step) == 10

    def test_resumed_trajectory_is_bit_identical(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = _cfg(checkpoint_dir=ckpt_dir, checkpoint_every=0,
                   auto_resume=True)
        mesh = host_cpu_mesh(W)

        # Uninterrupted: 6 steps.
        tr_a = Trainer(cfg.replace(checkpoint_dir=None, auto_resume=False),
                       mesh=mesh)
        for _ in range(6):
            tr_a.state, ma = tr_a.train_step(
                tr_a.state, tr_a.dataset.x_train, tr_a.dataset.y_train,
                tr_a.dataset.shard_indices)

        # Interrupted at 3 + resumed for 3: same final state.
        tr_b = Trainer(cfg, mesh=mesh)
        for _ in range(3):
            tr_b.state, _ = tr_b.train_step(
                tr_b.state, tr_b.dataset.x_train, tr_b.dataset.y_train,
                tr_b.dataset.shard_indices)
        tr_b.save()
        tr_c = Trainer(cfg, mesh=mesh)
        assert int(tr_c.state.step) == 3
        for _ in range(3):
            tr_c.state, mc = tr_c.train_step(
                tr_c.state, tr_c.dataset.x_train, tr_c.dataset.y_train,
                tr_c.dataset.shard_indices)

        np.testing.assert_array_equal(
            np.asarray(ma["train/loss"]), np.asarray(mc["train/loss"]))
        for a, b in zip(jax.tree.leaves(tr_a.state.params),
                        jax.tree.leaves(tr_c.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
