"""Telemetry subsystem unit tests: in-graph diagnostics math, the
non-blocking metric writer's queue policy, and run accounting.

Everything here is pure-CPU and fast — no model, no train step. The
diagnostics are checked against independent numpy derivations (not
against themselves), and the writer tests use ``start=False`` so the
queue policy is observed deterministically without thread timing.
"""

import io
import json
import os
import threading

import numpy as np

import jax.numpy as jnp

from mercury_tpu.config import TrainConfig
from mercury_tpu.obs.accounting import (
    ThroughputMeter,
    analytic_flops_per_step,
    peak_flops,
)
from mercury_tpu.obs.diagnostics import (
    clip_fraction,
    ema_drift,
    ess_fraction,
    global_grad_norm,
    table_age_summary,
    table_ages,
)
from mercury_tpu.obs.manifest import build_run_manifest, write_run_manifest
from mercury_tpu.obs.writer import (
    AsyncMetricWriter,
    HeartbeatSink,
    HeartbeatShardSink,
    JsonlSink,
)
from mercury_tpu.sampling.scoretable import refresh_period


# ------------------------------------------------------------- diagnostics
class TestEssFraction:
    def test_uniform_weights_are_exactly_one(self):
        # The uniform baseline feeds scaled_probs == 1 (unit weights):
        # ESS must land exactly at 1.0, not merely near it.
        assert float(ess_fraction(jnp.ones(64))) == 1.0

    def test_equal_nonunit_probs_still_one(self):
        b = 16
        probs = jnp.full((b,), 1.0 / b)
        assert float(ess_fraction(probs)) > 0.999

    def test_single_dominant_sample_approaches_one_over_b(self):
        b = 32
        # One tiny scaled prob → one huge weight dominating the batch.
        probs = jnp.ones(b).at[0].set(1e-6)
        ess = float(ess_fraction(probs))
        assert abs(ess - 1.0 / b) < 1e-3

    def test_matches_numpy_formula(self, rng):
        probs = rng.uniform(0.1, 2.0, size=24).astype(np.float32)
        w = 1.0 / probs
        expect = (w.sum() ** 2) / (24 * (w**2).sum())
        assert abs(float(ess_fraction(jnp.asarray(probs))) - expect) < 1e-5


class TestClipFraction:
    def test_counts_floored_scores(self):
        # With EMA 0 and alpha 0.5, smoothed score == loss: the two zero
        # losses sit at/below the floor, the positive one doesn't.
        scores = jnp.array([0.0, 0.0, 1.0])
        ema = jnp.zeros(())
        assert abs(float(clip_fraction(scores, ema, 0.5)) - 2 / 3) < 1e-6

    def test_positive_ema_lifts_everything_off_floor(self):
        scores = jnp.zeros(8)
        ema = jnp.asarray(2.0)
        assert float(clip_fraction(scores, ema, 0.5)) == 0.0


class TestEmaDrift:
    def test_signed_difference(self):
        assert float(ema_drift(jnp.asarray(3.0), jnp.asarray(1.0))) == 2.0
        assert float(ema_drift(jnp.asarray(0.5), jnp.asarray(1.0))) == -0.5


class TestTableAges:
    def test_window_is_age_zero_and_oldest_is_period_minus_one(self):
        n_slots, refresh = 12, 3
        period = refresh_period(n_slots, refresh)  # 4 sweeps cover the table
        ages = np.asarray(table_ages(jnp.asarray(0), n_slots, refresh))
        # This step's window [0, 3) is fresh.
        assert ages[:refresh].tolist() == [0.0, 0.0, 0.0]
        # The slot just behind the window is the oldest.
        assert ages.max() == period - 1
        assert ages[refresh] == period - 1

    def test_cursor_advance_rotates_ages(self):
        n_slots, refresh = 12, 3
        a0 = np.asarray(table_ages(jnp.asarray(0), n_slots, refresh))
        a1 = np.asarray(table_ages(jnp.asarray(refresh), n_slots, refresh))
        # One refresh later every slot's age pattern rotates by one window.
        assert np.array_equal(np.roll(a0, refresh), a1)

    def test_summary_min_mean_max(self):
        n_slots, refresh = 10, 3
        mn, mean, mx = table_age_summary(jnp.asarray(3), n_slots, refresh)
        ages = np.asarray(table_ages(jnp.asarray(3), n_slots, refresh))
        assert float(mn) == ages.min() == 0.0
        assert float(mx) == ages.max()
        assert abs(float(mean) - ages.mean()) < 1e-6


class TestGlobalGradNorm:
    def test_matches_flat_l2_over_pytree(self, rng):
        tree = {
            "w": jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(3,)).astype(np.float32)),
        }
        flat = np.concatenate([np.asarray(v).ravel() for v in tree.values()])
        assert abs(float(global_grad_norm(tree))
                   - np.linalg.norm(flat)) < 1e-5


# ------------------------------------------------------------------ writer
class ListSink:
    def __init__(self):
        self.records = []
        self.closed = 0

    def write(self, record):
        self.records.append(record)

    def close(self):
        self.closed += 1


class TestAsyncMetricWriter:
    def test_records_arrive_in_order(self):
        sink = ListSink()
        w = AsyncMetricWriter([sink], start=False)
        for step in range(1, 6):
            w.write(step, {"train/loss": float(step)})
        w.flush()
        assert [r["step"] for r in sink.records] == [1, 2, 3, 4, 5]
        assert [r["train/loss"] for r in sink.records] == [1, 2, 3, 4, 5]

    def test_bounded_queue_drops_oldest_and_counts(self):
        sink = ListSink()
        w = AsyncMetricWriter([sink], capacity=3, start=False)
        for step in range(1, 6):
            w.write(step, {"v": step})
        assert w.dropped == 2
        w.flush()
        # Oldest two (steps 1, 2) were dropped; survivors carry the count.
        assert [r["step"] for r in sink.records] == [3, 4, 5]
        assert all(r["obs/dropped"] == 2.0 for r in sink.records)

    def test_device_arrays_and_chunk_series_reduce_to_floats(self):
        sink = ListSink()
        w = AsyncMetricWriter([sink], start=False)
        # Scan-chunked [K] series must reduce to the chunk mean.
        w.write(7, {"train/loss": jnp.array([1.0, 2.0, 3.0]),
                    "train/acc": jnp.asarray(0.5)})
        w.flush()
        (rec,) = sink.records
        assert rec["train/loss"] == 2.0
        assert rec["train/acc"] == 0.5
        assert isinstance(rec["train/loss"], float)

    def test_background_thread_drains_and_close_joins(self):
        sink = ListSink()
        before = threading.active_count()
        w = AsyncMetricWriter([sink])
        # Lazy start: no thread until the first write.
        assert threading.active_count() == before
        for step in range(1, 4):
            w.write(step, {"v": step})
        w.close()
        assert [r["step"] for r in sink.records] == [1, 2, 3]
        assert sink.closed == 1

    def test_close_is_idempotent_and_write_after_close_is_noop(self):
        sink = ListSink()
        w = AsyncMetricWriter([sink], start=False)
        w.write(1, {"v": 1})
        w.close()
        w.close()
        w.write(2, {"v": 2})
        assert [r["step"] for r in sink.records] == [1]
        assert sink.closed == 1

    def test_context_manager_closes(self):
        sink = ListSink()
        with AsyncMetricWriter([sink], start=False) as w:
            w.log_scalars(1, {"v": 1.0})  # MetricsLogger-compatible alias
        assert sink.closed == 1
        assert sink.records[0]["v"] == 1.0

    def test_failing_sink_never_raises_into_caller(self):
        class Boom:
            def write(self, record):
                raise RuntimeError("sink down")

            def close(self):
                raise RuntimeError("still down")

        ok = ListSink()
        w = AsyncMetricWriter([Boom(), ok], start=False)
        w.write(1, {"v": 1})
        w.flush()
        w.close()
        assert [r["step"] for r in ok.records] == [1]
        assert w.errors >= 1

    def test_none_sinks_are_filtered(self):
        # try_tensorboard_sink returns None when TB is absent; the
        # writer must accept that directly.
        w = AsyncMetricWriter([None, ListSink()], start=False)
        assert len(w.sinks) == 1
        w.close()

    def test_close_racing_inflight_drain_loses_nothing(self):
        # close() while the drain thread is mid-queue: every record
        # written before close() must reach the sink exactly once —
        # close drains the queue after joining the thread, and the two
        # paths must not double-emit. A slow sink keeps the race window
        # open for real.
        import time as _time

        class SlowSink(ListSink):
            def write(self, record):
                _time.sleep(0.002)
                super().write(record)

        sink = SlowSink()
        w = AsyncMetricWriter([sink])
        for step in range(1, 21):
            w.write(step, {"v": step})
        w.close()  # thread mid-drain: ~40 ms of sink work is queued
        assert [r["step"] for r in sink.records] == list(range(1, 21))
        assert sink.closed == 1

    def test_wedged_sink_drops_oldest_not_training(self):
        # A sink that blocks forever on its first write (wedged NFS /
        # TB): write() must keep returning instantly, the bounded queue
        # must rotate (drop-OLDEST), and close() must come back despite
        # the thread being stuck inside the sink.
        release = threading.Event()

        class WedgedSink(ListSink):
            def write(self, record):
                release.wait(timeout=30.0)
                super().write(record)

        import time as _time

        sink = WedgedSink()
        w = AsyncMetricWriter([sink], capacity=4)
        w.write(1, {"v": 1})
        # Wait until the drain thread has TAKEN record 1 (it is now
        # wedged inside the sink), so the drop accounting below is
        # deterministic rather than racing thread startup.
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline:
            with w._lock:
                if not w._q and w._busy:
                    break
            _time.sleep(0.001)
        for step in range(2, 11):
            w.write(step, {"v": step})  # returns instantly every time
        # 1 record wedged in the sink, 4 queued (7..10), 2..6 dropped.
        assert w.dropped == 5
        release.set()
        w.close()
        # The wedged record plus the queue's newest survivors landed,
        # in order, exactly once; survivors carry the drop count.
        assert [r["step"] for r in sink.records] == [1, 7, 8, 9, 10]
        assert sink.records[-1]["obs/dropped"] == 5.0

    def test_observer_sees_host_record_and_mutation_reaches_sinks(self):
        sink = ListSink()
        seen = []

        def observer(record):
            seen.append(dict(record))
            record["anomaly/triggers"] = 1.0  # may mutate in place

        w = AsyncMetricWriter([sink], start=False, observers=(observer,))
        w.write(3, {"train/loss": jnp.asarray(2.0)})
        w.flush()
        assert seen[0]["train/loss"] == 2.0  # host float, post device_get
        assert sink.records[0]["anomaly/triggers"] == 1.0

    def test_observer_exception_is_counted_not_raised(self):
        sink = ListSink()

        def bad(record):
            raise RuntimeError("observer down")

        w = AsyncMetricWriter([sink, None], start=False,
                              observers=(bad, None))
        w.write(1, {"v": 1.0})
        w.flush()
        assert [r["step"] for r in sink.records] == [1]
        assert w.errors == 1


class TestJsonlSink:
    def test_buffered_writes_land_on_close(self, tmp_path):
        sink = JsonlSink(str(tmp_path), flush_every=100)
        sink.write({"step": 1, "train/loss": 2.5})
        sink.write({"step": 2, "train/loss": 2.0})
        sink.close()
        lines = (tmp_path / "metrics.jsonl").read_text().splitlines()
        recs = [json.loads(l) for l in lines]
        assert [r["step"] for r in recs] == [1, 2]
        assert recs[0]["train/loss"] == 2.5
        sink.close()  # idempotent


class TestHeartbeatShardSink:
    def test_one_flushed_row_per_record_with_liveness_subset(self, tmp_path):
        sink = HeartbeatShardSink(str(tmp_path), process_index=3)
        sink.write({"step": 5.0, "time": 1005.0, "time/step": 0.1,
                    "train/loss": 2.0, "data/stall_s": 0.02})
        # Flushed on write — readable BEFORE close (the post-mortem
        # contract: a SIGKILLed host leaves its last state on disk).
        lines = (tmp_path / "heartbeat.h3.jsonl").read_text().splitlines()
        (row,) = [json.loads(l) for l in lines]
        assert row["step"] == 5 and row["host"] == 3
        assert row["time/step"] == 0.1
        assert row["data/stall_s"] == 0.02
        assert "train/loss" not in row  # liveness subset only
        sink.close()
        sink.close()  # idempotent
        sink.write({"step": 6.0})  # write-after-close is a no-op
        assert len((tmp_path / "heartbeat.h3.jsonl")
                   .read_text().splitlines()) == 1

    def test_size_capped_rotation_bounds_growth(self, tmp_path):
        # Rows are ~60 bytes; a 200-byte cap forces rotation every few
        # writes. The live shard must stay under cap+one row, with one
        # prior generation kept at <name>.1 — a flush-per-write sink can
        # no longer grow without bound.
        sink = HeartbeatShardSink(str(tmp_path), process_index=0,
                                  max_bytes=200)
        for step in range(40):
            sink.write({"step": float(step), "time": 1000.0 + step})
        sink.close()
        live = tmp_path / "heartbeat.h0.jsonl"
        prior = tmp_path / "heartbeat.h0.jsonl.1"
        assert sink.rotations > 1
        assert prior.exists()
        assert live.stat().st_size <= 300
        # Both generations hold intact JSON lines; the newest row is the
        # last write (nothing lost at the rotation boundary).
        rows = [json.loads(l) for l in
                (prior.read_text() + live.read_text()).splitlines()]
        assert rows[-1]["step"] == 39
        steps = [r["step"] for r in rows]
        assert steps == sorted(steps)

    def test_max_bytes_zero_disables_rotation(self, tmp_path):
        sink = HeartbeatShardSink(str(tmp_path), process_index=0,
                                  max_bytes=0)
        for step in range(50):
            sink.write({"step": float(step)})
        sink.close()
        assert sink.rotations == 0
        assert not (tmp_path / "heartbeat.h0.jsonl.1").exists()


class TestHeartbeatSink:
    def test_rate_limited_by_step_cadence(self):
        out = io.StringIO()
        hb = HeartbeatSink(every_steps=2, min_interval_s=0.0, stream=out)
        for step in range(1, 7):
            hb.write({"step": step, "train/loss": 1.0, "sampler/ess": 0.9})
        lines = out.getvalue().splitlines()
        # First record always prints; then only on every_steps boundaries.
        assert lines[0].startswith("step 1")
        assert [l.split()[1] for l in lines] == ["1", "2", "4", "6"]
        assert "ess 0.9" in lines[0]

    def test_optional_keys_absent_and_present(self):
        # Non-host_stream runs have no data/stall_s; pre-trigger runs
        # have no anomaly/triggers — the line simply omits them, and
        # grows the fields once the keys appear.
        out = io.StringIO()
        hb = HeartbeatSink(every_steps=1, min_interval_s=0.0, stream=out)
        hb.write({"step": 1, "train/loss": 1.0})
        hb.write({"step": 2, "train/loss": 0.9, "data/stall_s": 0.25,
                  "obs/dropped": 3.0, "anomaly/triggers": 2.0})
        first, second = out.getvalue().splitlines()
        assert "stall_s" not in first and "triggers" not in first
        assert first == "step 1  loss 1"
        assert "stall_s 0.25" in second
        assert "dropped 3" in second
        assert "triggers 2" in second


# -------------------------------------------------------------- accounting
class TestThroughputMeter:
    def test_tick_math_with_explicit_clock(self):
        m = ThroughputMeter(examples_per_step=10, flops_per_step=1e9,
                            device_kind="TPU v4")
        m.reset(0, now=100.0)
        out = m.tick(10, now=102.0)  # 10 steps in 2 s
        assert out["perf/steps_per_s"] == 5.0
        assert out["perf/examples_per_s"] == 50.0
        assert out["time/step"] == 0.2
        assert out["perf/flops_per_step"] == 1e9
        assert abs(out["perf/mfu"] - 1e9 * 5.0 / 275e12) < 1e-18

    def test_unknown_device_reports_zero_mfu(self):
        m = ThroughputMeter(examples_per_step=8, flops_per_step=1e9,
                            device_kind="CPU-of-some-kind")
        m.reset(0, now=0.0)
        out = m.tick(4, now=1.0)
        assert out["perf/mfu"] == 0.0
        assert out["perf/steps_per_s"] == 4.0

    def test_first_tick_without_reset_is_empty(self):
        m = ThroughputMeter(examples_per_step=8)
        assert m.tick(5, now=1.0) == {}
        assert m.tick(10, now=2.0)["perf/steps_per_s"] == 5.0


class TestPeakFlops:
    def test_known_and_unknown_kinds(self):
        assert peak_flops("TPU v4") == 275e12
        assert peak_flops("TPU v5 lite") == 197e12
        assert peak_flops("Intel Xeon") is None
        assert peak_flops(None) is None


class TestAnalyticFlops:
    def test_jitted_matmul_reports_positive_flops(self):
        import jax

        @jax.jit
        def f(a, b):
            return a @ b

        a = jnp.ones((16, 16))
        flops = analytic_flops_per_step(f, a, a)
        # CPU's cost model may legitimately be absent (None); when it
        # answers, the number must be positive and scale down with scan.
        if flops is not None:
            assert flops > 0
            assert analytic_flops_per_step(f, a, a, scan_steps=2) == flops / 2

    def test_unlowerable_fn_returns_none(self):
        assert analytic_flops_per_step(lambda x: x, 1.0) is None


# ---------------------------------------------------------------- manifest
class TestRunManifest:
    def test_build_has_required_fields(self):
        import jax

        from mercury_tpu.parallel.mesh import make_mesh

        config = TrainConfig(model="smallcnn", dataset="synthetic",
                             world_size=2, batch_size=8)
        mesh = make_mesh(2, config.mesh_axis)
        man = build_run_manifest(config, mesh, extra={"note": "test"})
        assert man["schema"] == "mercury_run_manifest_v1"
        assert man["config"]["model"] == "smallcnn"
        assert man["jax_version"] == jax.__version__
        assert man["mesh_shape"] == {config.mesh_axis: 2}
        assert man["device_count"] == jax.device_count()
        assert man["note"] == "test"
        assert "peak_flops" in man  # null on CPU — but always present

    def test_write_produces_json_file(self, tmp_path):
        config = TrainConfig(model="smallcnn", dataset="synthetic",
                             world_size=1, batch_size=8)
        path = write_run_manifest(str(tmp_path), config)
        assert os.path.basename(path) == "run_manifest.json"
        man = json.loads(open(path).read())
        assert man["run_name"] == config.run_name()
        assert man["config"]["batch_size"] == 8
