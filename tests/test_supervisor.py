"""HostSupervisor (``mercury_tpu/runtime/supervisor.py``): restart
budget/backoff machinery, the degradation ladder
async → sync → frozen → uniform, recovery probing, and the trainer
integration — a chaos run past the restart budget must end degraded but
GREEN with uniform sampling (``sampler/is_active=0``), and a prefetch
restart must resume from the stream cursor bit-identically."""

import time

import numpy as np
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.runtime.supervisor import LEVEL_NAMES, HostSupervisor
from mercury_tpu.train.trainer import Trainer


class FakeFleet:
    """A supervisable unit with scriptable liveness/restart behavior."""

    def __init__(self, fail_restarts=0):
        self.up = True
        self.restarts = 0
        self.fail_restarts = fail_restarts   # first N restarts raise

    def alive(self):
        return self.up

    def restart(self):
        if self.restarts < self.fail_restarts:
            self.restarts += 1
            raise RuntimeError("injected restart failure")
        self.restarts += 1
        self.up = True


def make_sup(**kw):
    base = dict(restart_budget=3, backoff_s=0.0, probe_every=0, poll_s=0.0)
    base.update(kw)
    return HostSupervisor(**base)


class TestRestartMachinery:
    def test_dead_unit_restarted_on_tick(self):
        sup = make_sup()
        fleet = FakeFleet()
        sup.register_unit("scorer", fleet.alive, fleet.restart,
                          escalates=True)
        fleet.up = False
        sup.tick(step=1)
        assert fleet.up and fleet.restarts == 1
        assert sup.stats()["supervisor/restarts"] == 1.0
        assert sup.level() == 0

    def test_units_down_gauge(self):
        sup = make_sup(restart_budget=0)
        fleet = FakeFleet()
        sup.register_unit("scorer", fleet.alive, fleet.restart)
        assert sup.stats()["supervisor/units_down"] == 0.0
        fleet.up = False
        sup.tick(step=1)
        assert sup.stats()["supervisor/units_down"] == 1.0

    def test_escalating_exhaustion_degrades(self):
        sup = make_sup(restart_budget=1)
        fleet = FakeFleet()
        sup.register_unit("scorer", fleet.alive, fleet.restart,
                          escalates=True)
        fleet.up = False
        sup.tick(step=1)          # restart 1/1
        fleet.up = False
        sup.tick(step=2)          # budget exhausted -> degrade to sync
        assert sup.level() == 1
        sup.tick(step=3)          # exhaustion latched: no degrade-per-tick
        assert sup.level() == 1
        assert sup.stats()["supervisor/degradations"] == 1.0

    def test_non_escalating_exhaustion_stays_level0(self):
        sup = make_sup(restart_budget=0)
        pipe = FakeFleet()
        sup.register_unit("prefetch", pipe.alive, pipe.restart,
                          escalates=False)
        pipe.up = False
        sup.tick(step=1)
        assert sup.level() == 0
        assert not sup.request_restart("prefetch", step=1)

    def test_request_restart_honors_budget(self):
        sup = make_sup(restart_budget=2)
        pipe = FakeFleet()
        sup.register_unit("prefetch", pipe.alive, pipe.restart)
        assert sup.request_restart("prefetch", step=1)
        assert sup.request_restart("prefetch", step=2)
        assert not sup.request_restart("prefetch", step=3)
        assert pipe.restarts == 2
        assert not sup.request_restart("unknown", step=3)

    def test_failed_restart_consumes_budget(self):
        sup = make_sup(restart_budget=1)
        fleet = FakeFleet(fail_restarts=5)
        sup.register_unit("scorer", fleet.alive, fleet.restart,
                          escalates=True)
        fleet.up = False
        sup.tick(step=1)          # restart attempt raises
        assert not fleet.up
        fleet.up = False
        sup.tick(step=2)          # budget gone -> ladder
        assert sup.level() == 1


class TestDegradationLadder:
    def test_ladder_order_is_exact(self):
        sup = make_sup()
        seen = [sup.level_name()]
        for i in range(4):
            sup.report_failure("test", step=i, exc=RuntimeError("x"))
            seen.append(sup.level_name())
        assert seen == ["async", "sync", "frozen", "uniform", "uniform"]
        assert LEVEL_NAMES == ("async", "sync", "frozen", "uniform")

    def test_uniform_flips_sampler_inactive(self):
        sup = make_sup()
        for i in range(3):
            assert sup.sampler_active()
            sup.report_failure("test", step=i, exc=RuntimeError("x"))
        assert not sup.sampler_active()
        assert sup.stats()["sampler/is_active"] == 0.0
        assert sup.stats()["supervisor/level"] == 3.0

    def test_probe_success_climbs_and_final_climb_revives(self):
        sup = make_sup(probe_every=1)
        calls = []
        sup.set_ladder(probe=lambda: calls.append("probe"),
                       revive=lambda: calls.append("revive"))
        sup.report_failure("a", 0, RuntimeError("x"))
        sup.report_failure("b", 0, RuntimeError("x"))
        assert sup.level() == 2
        sup.tick(step=1)                   # probe ok -> frozen -> sync
        assert sup.level() == 1
        assert calls == ["probe"]
        sup.tick(step=2)                   # revive + probe -> async
        assert sup.level() == 0
        assert calls == ["probe", "revive", "probe"]
        assert sup.stats()["supervisor/recoveries"] == 2.0

    def test_probe_failure_escalates(self):
        def bad_probe():
            raise RuntimeError("still broken")

        sup = make_sup(probe_every=1)
        sup.set_ladder(probe=bad_probe, revive=lambda: None)
        sup.report_failure("a", 0, RuntimeError("x"))
        sup.tick(step=1)
        assert sup.level() == 2
        sup.tick(step=2)
        assert sup.level() == 3
        sup.tick(step=3)                   # already uniform: stays
        assert sup.level() == 3

    def test_recovery_to_nominal_resets_escalating_budgets(self):
        sup = make_sup(restart_budget=1, probe_every=1)
        fleet = FakeFleet()
        sup.register_unit("scorer", fleet.alive, fleet.restart,
                          escalates=True)
        sup.set_ladder(probe=lambda: None, revive=lambda: None)
        fleet.up = False
        sup.tick(step=1)                   # uses the whole budget
        fleet.up = False
        # Exhausted -> sync; the same tick's probe succeeds -> back to
        # async WITH the escalating budgets reset.
        sup.tick(step=2)
        assert sup.level() == 0
        assert sup.stats()["supervisor/degradations"] == 1.0
        assert sup.stats()["supervisor/recoveries"] == 1.0
        # The fleet is still down: the fresh budget restarts it again.
        sup.tick(step=3)
        assert fleet.up
        assert sup.level() == 0

    def test_transitions_recorded(self):
        sup = make_sup()
        sup.report_failure("sync refresh", 7, RuntimeError("x"))
        summ = sup.summary()
        assert summ["level_name"] == "sync"
        (t,) = summ["transitions"]
        assert (t["from"], t["to"], t["step"]) == ("async", "sync", 7)
        assert "sync refresh" in t["reason"]

    def test_stats_keys_registered(self):
        from mercury_tpu.obs.registry import METRIC_KEYS

        sup = make_sup()
        assert set(sup.stats()) <= set(METRIC_KEYS)


class TestMonitorThread:
    def test_poll_thread_lifecycle(self):
        sup = HostSupervisor(poll_s=0.01)
        fleet = FakeFleet()
        sup.register_unit("scorer", fleet.alive, fleet.restart)
        assert sup._thread is not None
        assert sup._thread.name == "mercury-supervisor"
        assert sup._thread.daemon
        fleet.up = False
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if sup.summary()["units"][0]["down"]:
                break
            time.sleep(0.01)
        # The monitor only STAMPS the death — restarts stay on tick().
        assert sup.summary()["units"][0]["down"]
        assert fleet.restarts == 0
        sup.close()
        sup.close()                        # idempotent
        assert not sup._thread.is_alive()

    def test_no_thread_when_poll_disabled(self):
        sup = make_sup(poll_s=0.0)
        assert sup._thread is None
        sup.close()


@pytest.fixture(scope="module")
def mesh():
    return host_cpu_mesh(4)


def sup_cfg(**kw):
    base = dict(
        model="smallcnn", dataset="synthetic", world_size=4, batch_size=8,
        presample_batches=2, num_epochs=1, steps_per_epoch=6, eval_every=0,
        log_every=0, heartbeat_every=0, checkpoint_every=0,
        compute_dtype="float32", seed=0, supervise=True,
        supervisor_backoff_s=0.0,
    )
    base.update(kw)
    return TrainConfig(**base)


def async_kw():
    return dict(sampler="scoretable", refresh_size=8, refresh_mode="async",
                scorer_workers=1, snapshot_every=2)


class TestTrainerIntegration:
    def test_scorer_death_restarted_within_budget(self, mesh):
        """A one-shot scorer death is restarted by tick() and the run
        stays at ladder level 0 with a generation-bumped fleet."""
        tr = Trainer(sup_cfg(fault_spec="scorer_die@step=1", **async_kw()),
                     mesh=mesh)
        try:
            tr._faults.note_step(1)
            deadline = time.monotonic() + 20.0
            while tr._scorer_fleet.alive() and time.monotonic() < deadline:
                tr._scorer_fleet.drain()   # unblock a queue-parked worker
                time.sleep(0.01)
            assert not tr._scorer_fleet.alive()
            tr.supervisor.tick(step=2)
            assert tr._scorer_fleet.alive()
            assert tr._scorer_fleet.summary()["generation"] == 1
            stats = tr.supervisor.stats()
            assert stats["supervisor/restarts"] == 1.0
            assert stats["supervisor/level"] == 0.0
            # -rN thread names: the restarted fleet is distinguishable in
            # the thread census (lint Layer C wildcards cover them).
            assert any(t.name.endswith("-r1")
                       for t in tr._scorer_fleet._threads)
        finally:
            tr.close()

    def test_chaos_past_budget_ends_uniform(self, mesh):
        """The acceptance run: a persistent scorer fault past the restart
        budget walks the full ladder and the run ends GREEN with uniform
        sampling — sampler/is_active=0 and a constant score table.

        Budget 0 keeps the walk deterministic: one worker death exhausts
        it (detection is the only async dependency — host_slow paces the
        loop so a parked worker always gets its firing window), and every
        later descent (sync-refresh failure, probe failure) happens ON
        the trainer thread. TWO concurrent scorer_die schedules: a step
        has one firing per entry, so the dying worker consuming one can
        never starve the trainer-thread probe of its failure — the
        recovery probe must keep FAILING for the ladder to descend."""
        tr = Trainer(sup_cfg(
            fault_spec=("scorer_die@step=1,every=1;"
                        "scorer_die@step=1,every=1;"
                        "host_slow@step=1,every=1,secs=0.02"),
            supervisor_restart_budget=0, supervisor_probe_every=1,
            supervisor_sync_every=1, steps_per_epoch=60,
            **async_kw()), mesh=mesh)
        try:
            tr.fit()                       # must not raise: degraded, green
            stats = tr.supervisor.stats()
            assert stats["supervisor/level"] == 3.0, tr.supervisor.summary()
            assert stats["sampler/is_active"] == 0.0
            assert stats["supervisor/degradations"] >= 3.0
            # The per-iteration level-3 pin leaves the table CONSTANT at
            # exit (zeroed scores), so the next inverse-CDF draw would be
            # exactly uniform.
            table = np.asarray(tr.state.scoretable.scores)
            assert np.all(np.isfinite(table))
            assert np.all(table == table.flat[0])
            assert tr._actuated_level == 3
            names = [t["to"] for t in tr.supervisor.summary()["transitions"]]
            assert names[-3:] == ["sync", "frozen", "uniform"] or \
                "uniform" in names
        finally:
            tr.close()

    def test_prefetch_restart_resumes_bitwise(self, mesh):
        """Prefetch death mid-run: the supervisor rebuilds the pipeline
        from the stream cursor, and the trajectory is BIT-identical to an
        uninterrupted run — no sample skipped or duplicated."""
        kw = dict(data_placement="host_stream", prefetch_depth=2,
                  batch_size=4, steps_per_epoch=8)
        ref = Trainer(sup_cfg(supervise=False, **kw), mesh=mesh)
        try:
            ref.fit()
            ref_params = [np.asarray(x) for x in
                          __import__("jax").tree_util.tree_leaves(
                              ref.state.params)]
        finally:
            ref.close()

        tr = Trainer(sup_cfg(fault_spec="prefetch_die@step=2", **kw),
                     mesh=mesh)
        try:
            tr.fit()
            stats = tr.supervisor.stats()
            assert stats["supervisor/restarts"] >= 1.0, (
                "the injected prefetch death was never restarted")
            assert tr._stream_gen >= 1
            got = [np.asarray(x) for x in
                   __import__("jax").tree_util.tree_leaves(tr.state.params)]
            for a, b in zip(ref_params, got):
                np.testing.assert_array_equal(a, b)
        finally:
            tr.close()

    def test_prefetch_budget_exhaustion_propagates(self, mesh):
        """escalates=False: past the budget a prefetch death is terminal
        — training cannot proceed without input, so fit() raises
        attributably instead of degrading."""
        tr = Trainer(sup_cfg(
            data_placement="host_stream", prefetch_depth=2, batch_size=4,
            steps_per_epoch=8, supervisor_restart_budget=0,
            fault_spec="prefetch_die@step=2"), mesh=mesh)
        try:
            with pytest.raises(RuntimeError, match="prefetch worker died"):
                tr.fit()
        finally:
            tr.close()


@pytest.mark.slow
class TestChaosMatrix:
    def test_concurrent_fault_matrix_stays_green(self, mesh, tmp_path):
        """The chaos CI scenario as a test: host_stream input + async
        scorer fleet + cadence checkpoints under four concurrent fault
        kinds. The run must complete, telemetry must account for every
        injection, and the final checkpoint must restore verified."""
        from mercury_tpu.train import checkpoint as ckpt

        before_failures = ckpt.write_failures()
        tr = Trainer(sup_cfg(
            data_placement="host_stream", prefetch_depth=2, batch_size=4,
            steps_per_epoch=24, log_every=6,
            checkpoint_dir=str(tmp_path), checkpoint_every=8,
            checkpoint_write_retries=2, checkpoint_retry_backoff_s=0.01,
            supervisor_restart_budget=2, supervisor_probe_every=4,
            supervisor_sync_every=2,
            fault_spec=("scorer_die@step=3,every=6;"
                        "prefetch_stall@step=2,every=5,secs=0.05;"
                        "ckpt_io_error@step=4,every=2;"
                        "sink_wedge@step=5,secs=0.05;"
                        "host_slow@step=6,secs=0.01"),
            **async_kw()), mesh=mesh)
        try:
            tr.fit()                       # degraded-but-green contract
            assert tr._faults.stats()["fault/injected"] >= 4.0
            # Every param finite; the sampler may be at any ladder level.
            for leaf in __import__("jax").tree_util.tree_leaves(
                    tr.state.params):
                assert np.all(np.isfinite(np.asarray(leaf)))
            # ckpt_io_error fired at least once on a cadence write and the
            # retry loop absorbed it (counted, not fatal).
            assert ckpt.write_failures() > before_failures
            restored, step = ckpt.restore_checkpoint(
                str(tmp_path), tr.state, verify=True)
            assert step >= 8
        finally:
            tr.close()
