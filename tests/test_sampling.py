"""Unit tests for the importance-sampling core (SURVEY.md §4: IS scoring,
EMA, unbiasedness of E[loss/(N·p)])."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mercury_tpu.sampling import (
    EMAState,
    draw,
    draw_with_replacement,
    ema_update,
    importance_probs,
    init_ema,
    init_groupwise,
    per_sample_loss,
    reweighted_loss,
    select_from_pool,
    uniform_selection,
    update_importance,
    window_indices,
)


class TestEMA:
    def test_bootstrap_first_update(self):
        # First update sets the raw value (util.py:209-211).
        ema = ema_update(init_ema(), jnp.asarray(3.0), alpha=0.9)
        assert float(ema.value) == pytest.approx(3.0)
        assert int(ema.count) == 1

    def test_blend(self):
        ema = ema_update(init_ema(), jnp.asarray(2.0), alpha=0.9)
        ema = ema_update(ema, jnp.asarray(4.0), alpha=0.9)
        assert float(ema.value) == pytest.approx(0.9 * 2.0 + 0.1 * 4.0)


class TestPerSampleLoss:
    def test_matches_manual_ce(self):
        logits = jnp.asarray([[2.0, 0.5, -1.0], [0.0, 0.0, 0.0]])
        labels = jnp.asarray([0, 2])
        losses = per_sample_loss(logits, labels)
        expected = -jax.nn.log_softmax(logits)[jnp.arange(2), labels]
        np.testing.assert_allclose(np.asarray(losses), np.asarray(expected), rtol=1e-6)

    def test_shape_is_per_sample(self):
        losses = per_sample_loss(jnp.zeros((7, 10)), jnp.zeros(7, jnp.int32))
        assert losses.shape == (7,)


class TestImportanceProbs:
    def test_normalized_distribution(self):
        losses = jnp.asarray([1.0, 2.0, 3.0])
        p = importance_probs(losses, jnp.asarray(2.0), alpha=0.5)
        assert float(jnp.sum(p)) == pytest.approx(1.0)
        # score_i = loss_i + 0.5·EMA (pytorch_collab.py:111-112)
        scores = np.array([2.0, 3.0, 4.0])
        np.testing.assert_allclose(np.asarray(p), scores / scores.sum(), rtol=1e-6)

    def test_hard_samples_more_likely(self):
        p = importance_probs(jnp.asarray([0.1, 5.0]), jnp.asarray(1.0), 0.5)
        assert float(p[1]) > float(p[0])


class TestDrawWithReplacement:
    def test_empirical_frequency_matches_probs(self):
        probs = jnp.asarray([0.7, 0.2, 0.1])
        idx = draw_with_replacement(jax.random.key(0), probs, 20000)
        freq = np.bincount(np.asarray(idx), minlength=3) / 20000
        np.testing.assert_allclose(freq, np.asarray(probs), atol=0.02)

    def test_replacement_allows_duplicates(self):
        idx = draw_with_replacement(jax.random.key(1), jnp.asarray([0.99, 0.01]), 50)
        assert len(np.unique(np.asarray(idx))) < 50  # dominated by index 0


class TestUnbiasedness:
    def test_is_estimator_unbiased(self):
        """E[mean(loss_i/(N·p_i))] over IS draws equals the uniform mean loss —
        the core Mercury estimator property (pytorch_collab.py:116,137)."""
        rng = np.random.default_rng(0)
        losses = jnp.asarray(rng.exponential(1.0, 64).astype(np.float32))
        n = losses.shape[0]
        probs = importance_probs(losses, jnp.asarray(1.0), 0.5)
        estimates = []
        for s in range(400):
            sel = draw_with_replacement(jax.random.key(s), probs, 16)
            scaled = probs[sel] * n
            estimates.append(float(reweighted_loss(losses[sel], scaled)))
        assert np.mean(estimates) == pytest.approx(float(jnp.mean(losses)), rel=0.05)

    def test_uniform_selection_unit_weights(self):
        sel, w = uniform_selection(jax.random.key(0), 100, 8)
        np.testing.assert_array_equal(np.asarray(w), np.ones(8, np.float32))
        assert np.asarray(sel).min() >= 0 and np.asarray(sel).max() < 100


class TestSelectFromPool:
    def test_full_selection_step(self):
        key = jax.random.key(0)
        losses = jnp.asarray(np.random.default_rng(0).exponential(1.0, 320).astype(np.float32))
        res = select_from_pool(key, losses, init_ema(), 32, 0.5, 0.9)
        assert res.selected.shape == (32,)
        assert res.scaled_probs.shape == (32,)
        # First step: EMA bootstraps to the pool mean.
        assert float(res.ema.value) == pytest.approx(float(jnp.mean(losses)), rel=1e-5)
        assert float(res.avg_pool_loss) == pytest.approx(float(jnp.mean(losses)), rel=1e-5)
        # scaled = p·N, and Σp over the whole pool is 1 → mean of p·N over
        # the *pool* is 1 (selected entries are biased high — that's the point).
        probs = importance_probs(losses, res.ema.value, 0.5)
        np.testing.assert_allclose(
            np.asarray(res.scaled_probs), np.asarray(probs[res.selected] * 320), rtol=1e-5
        )

    def test_deterministic_given_key(self):
        losses = jnp.linspace(0.1, 2.0, 64)
        r1 = select_from_pool(jax.random.key(7), losses, init_ema(), 8)
        r2 = select_from_pool(jax.random.key(7), losses, init_ema(), 8)
        np.testing.assert_array_equal(np.asarray(r1.selected), np.asarray(r2.selected))


class TestGroupwise:
    def test_window_wraps(self):
        state = init_groupwise(10)
        idx = window_indices(state, 4)
        np.testing.assert_array_equal(np.asarray(idx), [0, 1, 2, 3])
        state = update_importance(state, idx, jnp.ones(4))
        idx2 = window_indices(state, 8)
        np.testing.assert_array_equal(np.asarray(idx2), [4, 5, 6, 7, 8, 9, 0, 1])

    def test_draws_only_from_current_group(self):
        state = init_groupwise(20)
        idx = window_indices(state, 5)  # samples 0..4 → generation 1
        state = update_importance(state, idx, jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0]))
        sel, scaled = draw(state, jax.random.key(0), 100)
        assert np.asarray(sel).max() < 5  # only generation-1 samples drawable
        assert scaled.shape == (100,)

    def test_group_probs_shifted_by_mean(self):
        # p ∝ importance + mean(importance) over the group (util.py:144-147).
        state = init_groupwise(4)
        idx = window_indices(state, 4)
        imp = jnp.asarray([1.0, 1.0, 1.0, 5.0])
        state = update_importance(state, idx, imp)
        sel, _ = draw(state, jax.random.key(0), 40000)
        freq = np.bincount(np.asarray(sel), minlength=4) / 40000
        scores = np.asarray(imp) + np.asarray(imp).mean()
        np.testing.assert_allclose(freq, scores / scores.sum(), atol=0.02)


class TestGradNormScore:
    """``importance_score="grad_norm"`` — the Katharopoulos-Fleuret
    gradient-norm-bound scorer (arXiv:1803.00942, PAPERS.md)."""

    def test_equals_autodiff_per_sample_grad_norm(self):
        """||softmax − onehot||₂ must equal the true per-sample L2 norm of
        ∂CE/∂logits computed by autodiff."""
        from mercury_tpu.sampling.importance import (
            per_sample_grad_norm_bound,
            per_sample_loss,
        )

        logits = jax.random.normal(jax.random.key(0), (16, 10)) * 3.0
        labels = jax.random.randint(jax.random.key(1), (16,), 0, 10)

        got = per_sample_grad_norm_bound(logits, labels)

        def one_loss(z, y):
            return per_sample_loss(z[None], y[None])[0]

        grads = jax.vmap(jax.grad(one_loss))(logits, labels)  # [16, 10]
        want = jnp.linalg.norm(grads, axis=-1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_equals_autodiff_with_label_smoothing(self):
        """With smoothing the target is (1−ls)·onehot + ls/K — the score
        must track the gradient of the ACTUAL (smoothed) training loss."""
        from mercury_tpu.sampling.importance import (
            per_sample_grad_norm_bound,
            per_sample_loss,
        )

        ls = 0.1
        logits = jax.random.normal(jax.random.key(2), (16, 10)) * 3.0
        labels = jax.random.randint(jax.random.key(3), (16,), 0, 10)
        got = per_sample_grad_norm_bound(logits, labels, ls)

        def one_loss(z, y):
            return per_sample_loss(z[None], y[None], ls)[0]

        grads = jax.vmap(jax.grad(one_loss))(logits, labels)
        want = jnp.linalg.norm(grads, axis=-1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_misclassified_scores_higher(self):
        from mercury_tpu.sampling.importance import per_sample_grad_norm_bound

        # Confidently right vs confidently wrong: the wrong one's gradient
        # norm approaches √2, the right one's approaches 0.
        logits = jnp.array([[8.0, 0.0], [8.0, 0.0]], jnp.float32)
        labels = jnp.array([0, 1])
        s = np.asarray(per_sample_grad_norm_bound(logits, labels))
        assert s[1] > 100 * s[0]
        np.testing.assert_allclose(s[1], np.sqrt(2.0), rtol=1e-3)

    def test_training_learns_with_grad_norm_score(self):
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="smallcnn", dataset="synthetic", world_size=4, batch_size=8,
            presample_batches=2, steps_per_epoch=60, num_epochs=1,
            importance_score="grad_norm", eval_every=0, log_every=0,
            compute_dtype="float32", seed=0,
        )
        tr = Trainer(cfg, mesh=host_cpu_mesh(4))
        losses = []
        for _ in range(60):
            tr.state, m = tr.train_step(
                tr.state, tr.dataset.x_train, tr.dataset.y_train,
                tr.dataset.shard_indices)
            losses.append(float(m["train/loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.8
