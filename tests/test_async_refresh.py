"""Async scorer fleet (``config.refresh_mode = "async"``): importance
refresh moves off the training step onto background host threads that
rescore round-robin shard chunks with periodically-snapshotted params and
stream ``(slots, scores)`` into the device-resident table between steps.
The fused step keeps only decay → draw — zero scoring FLOPs in the hot
program (pinned by the graftlint ``async`` plan budget).

The contract tested here: an async chunk applied at age 0 is
BIT-identical to the in-graph refresh writing the same scores
(``apply_async_chunk`` routes through the same ``scatter_mean``, and
``stale_weighted``'s convex form makes ``age_weight == 1.0`` an IEEE
identity), and a chunk applied at age ``a`` equals applying it fresh and
letting the step's decay act ``a`` times — the host-side staleness
discount composes with the in-graph decay instead of fighting it."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def mesh():
    return host_cpu_mesh(4)


def async_cfg(**kw) -> TrainConfig:
    base = dict(
        model="smallcnn",
        dataset="synthetic",
        world_size=4,
        batch_size=8,
        presample_batches=2,
        num_epochs=1,
        steps_per_epoch=6,
        eval_every=0,
        log_every=0,
        heartbeat_every=0,
        checkpoint_every=0,
        compute_dtype="float32",
        seed=0,
        sampler="scoretable",
        refresh_size=8,
        refresh_mode="async",
        scorer_workers=1,
        snapshot_every=2,
    )
    base.update(kw)
    return TrainConfig(**base)


class TestAsyncApplyUnits:
    """Pure-function contract between the in-graph refresh and the
    host-side async apply."""

    def _fixture(self, L=64, R=16):
        key = jax.random.key(7)
        scores = jax.random.uniform(
            jax.random.fold_in(key, 0), (L,), minval=0.1, maxval=4.0)
        slots = (jnp.arange(R) * 3) % L  # distinct for R*3 <= 2L
        values = jax.random.uniform(
            jax.random.fold_in(key, 1), (R,), minval=0.1, maxval=4.0)
        ema = jnp.mean(scores)
        return key, scores, slots, values, ema

    def test_age0_bit_identical_to_ingraph_refresh(self):
        """apply_async_chunk at age_weight=1.0 on the decayed table IS
        the in-graph refresh — same scatter, bit-exact weighting."""
        from mercury_tpu.sampling.scoretable import (
            apply_async_chunk,
            decay_scores,
            table_refresh_draw,
        )

        key, scores, slots, values, ema = self._fixture()
        refreshed, _, _, _ = table_refresh_draw(
            key, scores, slots, values, ema, 8, decay=0.98)
        via_async = apply_async_chunk(
            decay_scores(scores.astype(jnp.float32), ema, 0.98),
            slots, values, ema, jnp.float32(1.0))
        np.testing.assert_array_equal(
            np.asarray(refreshed), np.asarray(via_async))

    def test_age0_matches_pallas_kernel(self):
        """...and therefore also matches the fused Pallas kernel's
        refreshed table (interpret mode on CPU, PR-1 tolerance)."""
        from mercury_tpu.ops import table_refresh_draw_pallas
        from mercury_tpu.sampling.scoretable import (
            apply_async_chunk,
            decay_scores,
        )

        key, scores, slots, values, ema = self._fixture()
        p_table, _, _, _ = table_refresh_draw_pallas(
            key, scores, slots, values, ema, 8, decay=0.98)
        via_async = apply_async_chunk(
            decay_scores(scores.astype(jnp.float32), ema, 0.98),
            slots, values, ema, jnp.float32(1.0))
        np.testing.assert_allclose(
            np.asarray(p_table), np.asarray(via_async), atol=1e-5)

    def test_aged_apply_equals_fresh_apply_then_decay(self):
        """With a constant EMA mean, applying a chunk at age ``a`` with
        weight γ^a equals applying it fresh and decaying the table ``a``
        times — staleness discounting commutes with the step's decay."""
        from mercury_tpu.sampling.scoretable import (
            apply_async_chunk,
            decay_scores,
        )

        _, scores, slots, values, mu = self._fixture()
        gamma, age = 0.9, 3

        def decay_n(t, n):
            for _ in range(n):
                t = decay_scores(t, mu, gamma)
            return t

        stale = apply_async_chunk(
            decay_n(scores, age), slots, values, mu,
            jnp.float32(gamma ** age))
        fresh_then_decayed = decay_n(
            apply_async_chunk(scores, slots, values, mu,
                              jnp.float32(1.0)), age)
        np.testing.assert_allclose(
            np.asarray(stale), np.asarray(fresh_then_decayed), rtol=1e-5)


class TestAsyncTrainer:
    def test_fit_runs_and_fleet_reports(self, mesh):
        t = Trainer(async_cfg(), mesh=mesh)
        try:
            out = t.fit(num_epochs=1)
            assert np.isfinite(out["test/eval_loss"])
            assert int(t.state.step) == 6
            fleet = t._scorer_fleet
            assert fleet is not None
            summary = fleet.summary()
            assert summary["chunks_scored"] >= 1
            assert summary["snapshots"] >= 1  # construction + cadence
            stats = fleet.stats()
            assert set(stats) == {
                "scorer/throughput",
                "sampler/refresh_lag_chunks",
                "sampler/score_staleness_mean",
                "sampler/score_staleness_max",
                "threads/queue_depth/scorer",
            }
            assert all(np.isfinite(v) for v in stats.values())
        finally:
            t.close()

    def test_applied_chunk_lands_bitwise(self, mesh):
        """A chunk scored synchronously and pushed through the trainer's
        jitted apply lands in the table bit-identically: at weight 1.0
        every touched slot holds exactly the fleet's score, every other
        slot is untouched."""
        t = Trainer(async_cfg(scorer_workers=1), mesh=mesh)
        try:
            fleet = t._scorer_fleet
            chunk = fleet.score_once()
            W, R = chunk.slots.shape
            assert (W, R) == (4, t.config.refresh_size)
            old = np.asarray(t.state.scoretable.scores)
            new_tab = t._apply_refresh(
                t.state.scoretable, t.state.ema.value,
                jnp.asarray(chunk.slots), jnp.asarray(chunk.scores),
                jnp.float32(1.0))
            new = np.asarray(new_tab.scores)
            for w in range(W):
                np.testing.assert_array_equal(
                    new[w, chunk.slots[w]], chunk.scores[w])
                mask = np.ones(old.shape[1], bool)
                mask[chunk.slots[w]] = False
                np.testing.assert_array_equal(new[w, mask], old[w, mask])
            # Cursor is fleet-owned under async: the apply leaves it be.
            np.testing.assert_array_equal(
                np.asarray(new_tab.cursor),
                np.asarray(t.state.scoretable.cursor))
        finally:
            t.close()

    @pytest.mark.parametrize("bad", [
        dict(sampler="pool"),
        dict(use_importance_sampling=False),
        dict(refresh_mode="weird"),
        dict(scorer_workers=0),
        dict(snapshot_every=0),
    ])
    def test_invalid_compositions_rejected(self, mesh, bad):
        with pytest.raises(ValueError):
            Trainer(async_cfg(**bad), mesh=mesh)

    def test_multiprocess_rejected_names_fleet_constraint(self, mesh,
                                                          monkeypatch):
        """Multi-controller async refresh is rejected, and the message
        names the REAL constraint — the fleet's per-process params
        snapshot and (slots, scores) chunk stream — not a stale
        single-controller precedent (host_stream no longer is one)."""
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ValueError,
                           match="scorer fleet.*per-process"):
            Trainer(async_cfg(), mesh=mesh)


class TestTrainerClose:
    """Trainer.close() regression: idempotent, ordering-safe, and safe on
    partially-constructed trainers (the fleet makes close() load-bearing
    — a leaked daemon thread would keep scoring a dead run)."""

    def test_close_is_idempotent(self, mesh):
        t = Trainer(async_cfg(), mesh=mesh)
        t.close()
        t.close()  # second close is a no-op, not an error
        assert t._scorer_fleet.summary()["closed"]

    def test_close_on_partially_constructed_trainer(self):
        # __init__ never ran: no config, logger, fleet, or stream pipe.
        Trainer.__new__(Trainer).close()

    def test_close_without_fleet(self, mesh):
        t = Trainer(async_cfg(refresh_mode="sync"), mesh=mesh)
        assert t._scorer_fleet is None
        t.close()
        t.close()


class TestAsyncHostStreamMatrix:
    """host_stream + async on a 4-way mesh — compile cost belongs in the
    slow tier (same budget call as TestHostStreamMatrix)."""

    pytestmark = pytest.mark.slow

    def test_w4_host_stream_async_fit(self, mesh):
        t = Trainer(async_cfg(data_placement="host_stream",
                              prefetch_depth=2, steps_per_epoch=6),
                    mesh=mesh)
        try:
            out = t.fit(num_epochs=1)
            assert np.isfinite(out["test/eval_loss"])
            assert int(t.state.step) == 6
            assert t._scorer_fleet.summary()["chunks_scored"] >= 1
        finally:
            t.close()
