"""Groupwise-sampler training integration (sampler="groupwise"): the
Groupwise_Sampler formulation (util.py:94-160) as a first-class strategy in
the SPMD step — persistent shard-wide importance, sliding-window refresh,
draws from the newest group."""

import jax
import numpy as np
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.parallel.mesh import host_cpu_mesh
from mercury_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def mesh():
    return host_cpu_mesh(8)


def gw_config(**kw) -> TrainConfig:
    base = dict(
        model="smallcnn", dataset="synthetic", world_size=8, batch_size=8,
        presample_batches=2, sampler="groupwise", num_epochs=1,
        steps_per_epoch=15, eval_every=0, log_every=0,
        compute_dtype="float32", seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


class TestGroupwiseTraining:
    def test_state_has_groupwise_arrays(self, mesh):
        tr = Trainer(gw_config(), mesh=mesh)
        assert tr.state.groupwise is not None
        shard_len = int(tr.dataset.shard_indices.shape[1])
        assert tr.state.groupwise.importance.shape == (8, shard_len)
        assert tr.state.groupwise.generation.shape == (8,)

    def test_trains_and_generation_advances(self, mesh):
        tr = Trainer(gw_config(), mesh=mesh)
        losses = []
        for _ in range(15):
            tr.state, m = tr.train_step(
                tr.state, tr.dataset.x_train, tr.dataset.y_train,
                tr.dataset.shard_indices,
            )
            losses.append(float(m["train/loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert np.mean(losses[-5:]) < np.mean(losses[:5])
        # Every step refreshed one window → generation == step count.
        gen = np.asarray(tr.state.groupwise.generation)
        np.testing.assert_array_equal(gen, 15)
        # Cursor slid by pool_size each step, modulo shard length.
        shard_len = int(tr.dataset.shard_indices.shape[1])
        expect = (15 * 16) % shard_len
        np.testing.assert_array_equal(np.asarray(tr.state.groupwise.cursor), expect)

    def test_groupwise_under_scan_chunks(self, mesh):
        """The groupwise pytree (importance/generation/cursor) must carry
        correctly through the lax.scan chunked step."""
        tr = Trainer(gw_config(steps_per_epoch=6, scan_steps=3), mesh=mesh)
        for _ in range(2):
            tr.state, m = tr.train_step_many(
                tr.state, tr.dataset.x_train, tr.dataset.y_train,
                tr.dataset.shard_indices,
            )
        assert m["train/loss"].shape == (3,)
        np.testing.assert_array_equal(
            np.asarray(tr.state.groupwise.generation), 6
        )

    def test_importance_gets_written(self, mesh):
        tr = Trainer(gw_config(steps_per_epoch=3), mesh=mesh)
        for _ in range(3):
            tr.state, _ = tr.train_step(
                tr.state, tr.dataset.x_train, tr.dataset.y_train,
                tr.dataset.shard_indices,
            )
        imp = np.asarray(tr.state.groupwise.importance)
        # The first 3 windows (48 slots) hold real losses, not the init 1.0.
        touched = imp[:, :48]
        assert not np.allclose(touched, 1.0)
