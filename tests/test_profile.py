"""Timing-breakdown and profiler-trace smoke tests.

``timing_breakdown`` is the capability-parity answer to the reference's
manual five-segment wall-clock instrumentation; it must produce all six
keys as non-negative floats on a tiny CPU config (the numbers themselves
are platform noise — only shape and sanity are asserted). ``trace`` must
actually drive ``jax.profiler`` and leave a trace artifact on disk.
"""

import os

import jax
import jax.numpy as jnp
import pytest

from mercury_tpu.config import TrainConfig
from mercury_tpu.train.profile import timing_breakdown, trace
from mercury_tpu.train.trainer import Trainer


EXPECTED_KEYS = {"step_time", "ff_time", "bp_time", "fb_time",
                 "is_time", "sync_time"}


@pytest.fixture(scope="module")
def tiny_trainer():
    config = TrainConfig(
        model="smallcnn",
        dataset="synthetic",
        world_size=8,
        batch_size=8,
        presample_batches=3,
        num_epochs=1,
        steps_per_epoch=2,
        eval_every=0,
        log_every=0,
        compute_dtype="float32",
        seed=0,
    )
    trainer = Trainer(config)
    yield trainer
    trainer.close()


def test_timing_breakdown_six_nonnegative_segments(tiny_trainer):
    out = timing_breakdown(tiny_trainer, iters=2)
    assert set(out) == EXPECTED_KEYS
    for key, value in out.items():
        assert isinstance(value, float), key
        assert value >= 0.0, f"{key} negative: {value}"
    # bp_time is defined as max(fb - ff, 0): it can never exceed the raw
    # forward+backward median it was derived from.
    assert out["bp_time"] <= out["fb_time"] + 1e-12


def test_trace_writes_profile_artifacts(tmp_path):
    log_dir = str(tmp_path / "trace")
    with trace(log_dir):
        jnp.asarray(jax.jit(lambda x: x * 2)(jnp.ones((8, 8)))).block_until_ready()
    found = [os.path.join(root, f)
             for root, _, files in os.walk(log_dir) for f in files]
    assert found, "trace() produced no profile artifacts"
