"""Data layer tests: Dirichlet partition invariants (disjoint, cover,
min-size — mirror of data_loader.py:145), pipeline contract, augmentation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mercury_tpu.data import (
    augment_batch,
    load_dataset,
    make_sharded_dataset,
    normalize_images,
    partition_data,
    record_class_histograms,
)
from mercury_tpu.data.cifar import CIFAR10_MEAN, CIFAR10_STD, synthetic_cifar
from mercury_tpu.data.pipeline import ShardStream, eval_batches, init_shard_streams, next_pool


@pytest.fixture(scope="module")
def labels():
    return np.random.default_rng(0).integers(0, 10, 2000).astype(np.int32)


class TestPartition:
    def test_hetero_disjoint_and_cover(self, labels):
        shards = partition_data(labels, 4, mode="hetero", alpha=0.5, seed=102)
        allidx = np.concatenate(shards)
        assert len(allidx) == len(labels)
        assert len(np.unique(allidx)) == len(labels)  # disjoint + cover

    def test_hetero_min_size(self, labels):
        # Retry loop guarantees every shard ≥ 10 (data_loader.py:145).
        shards = partition_data(labels, 8, mode="hetero", alpha=0.1, seed=102)
        assert min(len(s) for s in shards) >= 10

    def test_hetero_is_heterogeneous(self, labels):
        """Low α must produce skewed class distributions (the point of the
        Dirichlet partition)."""
        shards = partition_data(labels, 4, mode="hetero", alpha=0.1, seed=102)
        hists = record_class_histograms(labels, shards)
        # At least one worker should be missing some class or heavily skewed.
        fracs = []
        for h in hists:
            total = sum(h.values())
            top = max(h.values())
            fracs.append(top / total)
        assert max(fracs) > 0.25  # well above the uniform 10%

    def test_homo_equal_split(self, labels):
        shards = partition_data(labels, 4, mode="homo", seed=0)
        sizes = [len(s) for s in shards]
        assert max(sizes) - min(sizes) <= 1
        assert sum(sizes) == len(labels)

    def test_deterministic_given_seed(self, labels):
        a = partition_data(labels, 4, mode="hetero", alpha=0.5, seed=7)
        b = partition_data(labels, 4, mode="hetero", alpha=0.5, seed=7)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


class TestCifarLoad:
    def test_synthetic_fallback_shapes(self):
        train, test, info = load_dataset("synthetic", synthetic_train_size=256,
                                         synthetic_test_size=64)
        x, y = train
        assert x.shape == (256, 32, 32, 3) and x.dtype == np.uint8
        assert y.shape == (256,) and y.dtype == np.int32
        assert info["num_classes"] == 10

    def test_synthetic_deterministic(self):
        a, _, _ = load_dataset("synthetic", synthetic_train_size=64, seed=3)
        b, _, _ = load_dataset("synthetic", synthetic_train_size=64, seed=3)
        np.testing.assert_array_equal(a[0], b[0])

    def test_synthetic_learnable_structure(self):
        """Class templates must separate: same-class images correlate more
        than cross-class on average."""
        (x, y), _, _ = load_dataset("synthetic", synthetic_train_size=512)
        xf = x.reshape(len(x), -1).astype(np.float32)
        xf -= xf.mean(axis=1, keepdims=True)
        xf /= np.linalg.norm(xf, axis=1, keepdims=True) + 1e-8
        same, diff = [], []
        for i in range(0, 200, 2):
            for j in range(1, 200, 7):
                c = float(xf[i] @ xf[j])
                (same if y[i] == y[j] else diff).append(c)
        assert np.mean(same) > np.mean(diff) + 0.05


class TestPipeline:
    def test_normalize(self):
        img = np.full((2, 32, 32, 3), 255, np.uint8)
        out = np.asarray(normalize_images(jnp.asarray(img), CIFAR10_MEAN, CIFAR10_STD))
        np.testing.assert_allclose(out[0, 0, 0], (1.0 - CIFAR10_MEAN) / CIFAR10_STD, rtol=1e-5)

    def test_augment_shapes_and_determinism(self):
        imgs = jnp.asarray(np.random.default_rng(0).normal(0, 1, (4, 32, 32, 3)),
                           jnp.float32)
        a = augment_batch(jax.random.key(0), imgs)
        b = augment_batch(jax.random.key(0), imgs)
        assert a.shape == imgs.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = augment_batch(jax.random.key(1), imgs)
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_cutout(self):
        imgs = jnp.ones((2, 32, 32, 3), jnp.float32)
        out = augment_batch(jax.random.key(0), imgs, pad=0, use_cutout=True)
        # Some pixels must be zeroed by the cutout square.
        assert float(jnp.sum(out == 0)) > 0

    def test_index_carrying_contract(self):
        """Batches carry global sample ids (cifar10/datasets.py:93)."""
        train, test, info = load_dataset("synthetic", synthetic_train_size=64,
                                         synthetic_test_size=16)
        shards = [np.arange(32), np.arange(32, 64)]
        ds = make_sharded_dataset(train, test, shards, info["mean"], info["std"], 10)
        batch = ds.gather_batch(jnp.asarray([5, 40, 63]))
        np.testing.assert_array_equal(np.asarray(batch.index), [5, 40, 63])
        np.testing.assert_array_equal(np.asarray(batch.label),
                                      train[1][np.array([5, 40, 63])])

    def test_shard_tiling(self):
        """Unequal shards are cyclically tiled to the max length."""
        train, test, info = load_dataset("synthetic", synthetic_train_size=64,
                                         synthetic_test_size=16)
        shards = [np.arange(10), np.arange(10, 64)]
        ds = make_sharded_dataset(train, test, shards, info["mean"], info["std"], 10)
        assert ds.shard_indices.shape == (2, 54)
        row0 = np.asarray(ds.shard_indices[0])
        np.testing.assert_array_equal(row0[:10], np.arange(10))
        np.testing.assert_array_equal(row0[10:20], np.arange(10))  # wrapped
        assert int(ds.shard_sizes[0]) == 10

    def test_stream_wraps_and_reshuffles(self):
        stream = init_shard_streams(jax.random.key(0), 1, 10)
        s = ShardStream(perm=stream.perm[0], cursor=stream.cursor[0])
        first_epoch = []
        s1, slots1 = next_pool(s, jax.random.key(1), 6)
        first_epoch.extend(np.asarray(slots1))
        # Next pull of 6 exceeds the remaining 4 → reshuffle + restart
        # (Trainer.get_next wrapping, pytorch_collab.py:74-82).
        s2, slots2 = next_pool(s1, jax.random.key(2), 6)
        assert int(s2.cursor) == 6
        assert len(np.unique(np.asarray(slots2))) == 6  # without replacement

    def test_stream_epoch_covers_all(self):
        stream = init_shard_streams(jax.random.key(0), 1, 12)
        s = ShardStream(perm=stream.perm[0], cursor=stream.cursor[0])
        seen = []
        for i in range(3):
            s, slots = next_pool(s, jax.random.key(i + 10), 4)
            seen.extend(np.asarray(slots))
        assert sorted(seen) == list(range(12))  # one full epoch, no repeats

    def test_eval_batches_cover_with_mask(self):
        plan = eval_batches(10, 4)
        assert len(plan) == 3
        assert plan[-1][1] == 2  # last batch valid count
        covered = sorted(set(int(i) for idx, valid in plan for i in idx[:valid]))
        assert covered == list(range(10))
