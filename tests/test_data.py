"""Data layer tests: Dirichlet partition invariants (disjoint, cover,
min-size — mirror of data_loader.py:145), pipeline contract, augmentation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mercury_tpu.data import (
    augment_batch,
    load_dataset,
    make_sharded_dataset,
    normalize_images,
    partition_data,
    record_class_histograms,
)
from mercury_tpu.data.cifar import CIFAR10_MEAN, CIFAR10_STD, synthetic_cifar
from mercury_tpu.data.pipeline import ShardStream, eval_batches, init_shard_streams, next_pool


@pytest.fixture(scope="module")
def labels():
    return np.random.default_rng(0).integers(0, 10, 2000).astype(np.int32)


class TestPartition:
    def test_hetero_disjoint_and_cover(self, labels):
        shards = partition_data(labels, 4, mode="hetero", alpha=0.5, seed=102)
        allidx = np.concatenate(shards)
        assert len(allidx) == len(labels)
        assert len(np.unique(allidx)) == len(labels)  # disjoint + cover

    def test_hetero_min_size(self, labels):
        # Retry loop guarantees every shard ≥ 10 (data_loader.py:145).
        shards = partition_data(labels, 8, mode="hetero", alpha=0.1, seed=102)
        assert min(len(s) for s in shards) >= 10

    def test_hetero_is_heterogeneous(self, labels):
        """Low α must produce skewed class distributions (the point of the
        Dirichlet partition)."""
        shards = partition_data(labels, 4, mode="hetero", alpha=0.1, seed=102)
        hists = record_class_histograms(labels, shards)
        # At least one worker should be missing some class or heavily skewed.
        fracs = []
        for h in hists:
            total = sum(h.values())
            top = max(h.values())
            fracs.append(top / total)
        assert max(fracs) > 0.25  # well above the uniform 10%

    def test_homo_equal_split(self, labels):
        shards = partition_data(labels, 4, mode="homo", seed=0)
        sizes = [len(s) for s in shards]
        assert max(sizes) - min(sizes) <= 1
        assert sum(sizes) == len(labels)

    def test_deterministic_given_seed(self, labels):
        a = partition_data(labels, 4, mode="hetero", alpha=0.5, seed=7)
        b = partition_data(labels, 4, mode="hetero", alpha=0.5, seed=7)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


class TestCifarLoad:
    def test_synthetic_fallback_shapes(self):
        train, test, info = load_dataset("synthetic", synthetic_train_size=256,
                                         synthetic_test_size=64)
        x, y = train
        assert x.shape == (256, 32, 32, 3) and x.dtype == np.uint8
        assert y.shape == (256,) and y.dtype == np.int32
        assert info["num_classes"] == 10

    def test_synthetic_deterministic(self):
        a, _, _ = load_dataset("synthetic", synthetic_train_size=64, seed=3)
        b, _, _ = load_dataset("synthetic", synthetic_train_size=64, seed=3)
        np.testing.assert_array_equal(a[0], b[0])

    def test_synthetic_hard_properties(self):
        """The sample-efficiency task: 20 classes, heavy-tailed per-sample
        difficulty, ~5% train-label noise with CLEAN test labels."""
        train, test, info = load_dataset("synthetic_hard",
                                         synthetic_train_size=2000,
                                         synthetic_test_size=400, seed=0)
        assert info["num_classes"] == 20
        x, y = train
        assert x.shape == (2000, 32, 32, 3) and x.dtype == np.uint8
        assert y.min() >= 0 and y.max() < 20
        # Label noise applied to train only: regenerate without noise via
        # the underlying generator and compare flip fractions.
        from mercury_tpu.data.cifar import synthetic_cifar

        clean, clean_test = synthetic_cifar(
            20, 2000, 400, seed=0, difficulty="heavy_tail", label_noise=0.0
        )
        flips = float(np.mean(clean[1] != y))
        assert 0.02 < flips < 0.09, flips
        np.testing.assert_array_equal(clean_test[1], test[1])  # test clean
        # Deterministic across loads.
        train2, _, _ = load_dataset("synthetic_hard",
                                    synthetic_train_size=2000,
                                    synthetic_test_size=400, seed=0)
        np.testing.assert_array_equal(train2[0], x)

    def test_synthetic_learnable_structure(self):
        """Class templates must separate: same-class images correlate more
        than cross-class on average."""
        (x, y), _, _ = load_dataset("synthetic", synthetic_train_size=512)
        xf = x.reshape(len(x), -1).astype(np.float32)
        xf -= xf.mean(axis=1, keepdims=True)
        xf /= np.linalg.norm(xf, axis=1, keepdims=True) + 1e-8
        same, diff = [], []
        for i in range(0, 200, 2):
            for j in range(1, 200, 7):
                c = float(xf[i] @ xf[j])
                (same if y[i] == y[j] else diff).append(c)
        assert np.mean(same) > np.mean(diff) + 0.05


def _fake_cifar_images(n, num_classes, seed):
    """Known uint8 NHWC images + labels for byte-exactness checks."""
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, (n, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, num_classes, n).astype(np.int32)
    return x, y


def _write_pickle_batch(path, x_nhwc, labels, label_key):
    """Serialize in the on-disk CIFAR batch layout: uint8 rows of 3072
    bytes, channel-major (R plane, G plane, B plane) — the format
    torchvision unpickles for the reference (data_loader.py:114-123)."""
    import pickle

    rows = x_nhwc.transpose(0, 3, 1, 2).reshape(len(x_nhwc), -1)
    with open(path, "wb") as f:
        pickle.dump({"data": rows, label_key: labels.tolist()}, f)


class TestRealCifarIngest:
    """The real-data byte path (cifar.py pickle/tar/npz ingest): fixture
    files in the standard formats must come back byte-exact NHWC uint8.
    This code otherwise only runs the day real data appears."""

    def test_pickle_batches_byte_exact(self, tmp_path):
        bdir = tmp_path / "cifar-10-batches-py"
        bdir.mkdir()
        xs, ys = [], []
        for i in range(1, 6):
            x, y = _fake_cifar_images(8, 10, seed=i)
            _write_pickle_batch(bdir / f"data_batch_{i}", x, y, "labels")
            xs.append(x)
            ys.append(y)
        xt, yt = _fake_cifar_images(6, 10, seed=99)
        _write_pickle_batch(bdir / "test_batch", xt, yt, "labels")

        train, test, info = load_dataset(
            "cifar10", data_dir=str(tmp_path), allow_synthetic=False
        )
        assert info["synthetic"] is False and info["num_classes"] == 10
        x_train, y_train = train
        assert x_train.dtype == np.uint8 and x_train.shape == (40, 32, 32, 3)
        np.testing.assert_array_equal(x_train, np.concatenate(xs))
        np.testing.assert_array_equal(y_train, np.concatenate(ys))
        np.testing.assert_array_equal(test[0], xt)
        np.testing.assert_array_equal(test[1], yt)

    def test_targz_extraction(self, tmp_path):
        """A cifar-10-python.tar.gz in the data root is extracted and then
        loaded through the same pickle path."""
        import tarfile

        stage = tmp_path / "stage" / "cifar-10-batches-py"
        stage.mkdir(parents=True)
        batches = {}
        for i in range(1, 6):
            x, y = _fake_cifar_images(4, 10, seed=10 + i)
            _write_pickle_batch(stage / f"data_batch_{i}", x, y, "labels")
            batches[i] = (x, y)
        xt, yt = _fake_cifar_images(4, 10, seed=50)
        _write_pickle_batch(stage / "test_batch", xt, yt, "labels")

        root = tmp_path / "root"
        root.mkdir()
        with tarfile.open(root / "cifar-10-python.tar.gz", "w:gz") as tf:
            tf.add(stage, arcname="cifar-10-batches-py")

        train, _, info = load_dataset(
            "cifar10", data_dir=str(root), allow_synthetic=False
        )
        assert info["synthetic"] is False
        np.testing.assert_array_equal(train[0][:4], batches[1][0])

    def test_npz_cache_byte_exact(self, tmp_path):
        x, y = _fake_cifar_images(16, 10, seed=7)
        xt, yt = _fake_cifar_images(8, 10, seed=8)
        np.savez(tmp_path / "cifar10.npz", x_train=x, y_train=y,
                 x_test=xt, y_test=yt)
        train, test, info = load_dataset(
            "cifar10", data_dir=str(tmp_path), allow_synthetic=False
        )
        assert info["synthetic"] is False
        np.testing.assert_array_equal(train[0], x)
        np.testing.assert_array_equal(train[1], y)
        np.testing.assert_array_equal(test[0], xt)
        assert train[1].dtype == np.int32

    def test_cifar100_fine_labels(self, tmp_path):
        bdir = tmp_path / "cifar-100-python"
        bdir.mkdir()
        x, y = _fake_cifar_images(12, 100, seed=3)
        _write_pickle_batch(bdir / "train", x, y, "fine_labels")
        xt, yt = _fake_cifar_images(6, 100, seed=4)
        _write_pickle_batch(bdir / "test", xt, yt, "fine_labels")
        train, test, info = load_dataset(
            "cifar100", data_dir=str(tmp_path), allow_synthetic=False
        )
        assert info["num_classes"] == 100 and info["synthetic"] is False
        np.testing.assert_array_equal(train[0], x)
        np.testing.assert_array_equal(train[1], y)
        np.testing.assert_array_equal(test[1], yt)

    def test_no_data_raises_when_synthetic_disallowed(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="MERCURY_TPU_DATA"):
            load_dataset("cifar10", data_dir=str(tmp_path),
                         allow_synthetic=False)

    def test_trainer_end_to_end_on_fixture_data(self, tmp_path):
        """The full Trainer path consumes fixture 'real' CIFAR: ingest →
        partition → sharded dataset → one IS train step."""
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        bdir = tmp_path / "cifar-10-batches-py"
        bdir.mkdir()
        for i in range(1, 6):
            x, y = _fake_cifar_images(64, 10, seed=20 + i)
            _write_pickle_batch(bdir / f"data_batch_{i}", x, y, "labels")
        xt, yt = _fake_cifar_images(32, 10, seed=60)
        _write_pickle_batch(bdir / "test_batch", xt, yt, "labels")

        cfg = TrainConfig(model="smallcnn", dataset="cifar10",
                          data_dir=str(tmp_path), world_size=4, batch_size=4,
                          presample_batches=2, steps_per_epoch=1, num_epochs=1,
                          eval_every=0, log_every=0, compute_dtype="float32",
                          seed=0)
        tr = Trainer(cfg, mesh=host_cpu_mesh(4))
        assert tr.dataset.synthetic is False
        tr.state, m = tr.train_step(
            tr.state, tr.dataset.x_train, tr.dataset.y_train,
            tr.dataset.shard_indices,
        )
        assert np.isfinite(float(m["train/loss"]))


class TestPipeline:
    def test_normalize(self):
        img = np.full((2, 32, 32, 3), 255, np.uint8)
        out = np.asarray(normalize_images(jnp.asarray(img), CIFAR10_MEAN, CIFAR10_STD))
        np.testing.assert_allclose(out[0, 0, 0], (1.0 - CIFAR10_MEAN) / CIFAR10_STD, rtol=1e-5)

    def test_augment_shapes_and_determinism(self):
        imgs = jnp.asarray(np.random.default_rng(0).normal(0, 1, (4, 32, 32, 3)),
                           jnp.float32)
        a = augment_batch(jax.random.key(0), imgs)
        b = augment_batch(jax.random.key(0), imgs)
        assert a.shape == imgs.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = augment_batch(jax.random.key(1), imgs)
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_cutout(self):
        imgs = jnp.ones((2, 32, 32, 3), jnp.float32)
        out = augment_batch(jax.random.key(0), imgs, pad=0, use_cutout=True)
        # Some pixels must be zeroed by the cutout square.
        assert float(jnp.sum(out == 0)) > 0

    def test_index_carrying_contract(self):
        """Batches carry global sample ids (cifar10/datasets.py:93)."""
        train, test, info = load_dataset("synthetic", synthetic_train_size=64,
                                         synthetic_test_size=16)
        shards = [np.arange(32), np.arange(32, 64)]
        ds = make_sharded_dataset(train, test, shards, info["mean"], info["std"], 10)
        batch = ds.gather_batch(jnp.asarray([5, 40, 63]))
        np.testing.assert_array_equal(np.asarray(batch.index), [5, 40, 63])
        np.testing.assert_array_equal(np.asarray(batch.label),
                                      train[1][np.array([5, 40, 63])])

    def test_shard_tiling(self):
        """Unequal shards are cyclically tiled to the max length."""
        train, test, info = load_dataset("synthetic", synthetic_train_size=64,
                                         synthetic_test_size=16)
        shards = [np.arange(10), np.arange(10, 64)]
        ds = make_sharded_dataset(train, test, shards, info["mean"], info["std"], 10)
        assert ds.shard_indices.shape == (2, 54)
        row0 = np.asarray(ds.shard_indices[0])
        np.testing.assert_array_equal(row0[:10], np.arange(10))
        np.testing.assert_array_equal(row0[10:20], np.arange(10))  # wrapped
        assert int(ds.shard_sizes[0]) == 10

    def test_stream_wraps_and_reshuffles(self):
        stream = init_shard_streams(jax.random.key(0), 1, 10)
        s = ShardStream(perm=stream.perm[0], cursor=stream.cursor[0])
        first_epoch = []
        s1, slots1 = next_pool(s, jax.random.key(1), 6)
        first_epoch.extend(np.asarray(slots1))
        # Next pull of 6 exceeds the remaining 4 → reshuffle + restart
        # (Trainer.get_next wrapping, pytorch_collab.py:74-82).
        s2, slots2 = next_pool(s1, jax.random.key(2), 6)
        assert int(s2.cursor) == 6
        assert len(np.unique(np.asarray(slots2))) == 6  # without replacement

    def test_stream_epoch_covers_all(self):
        stream = init_shard_streams(jax.random.key(0), 1, 12)
        s = ShardStream(perm=stream.perm[0], cursor=stream.cursor[0])
        seen = []
        for i in range(3):
            s, slots = next_pool(s, jax.random.key(i + 10), 4)
            seen.extend(np.asarray(slots))
        assert sorted(seen) == list(range(12))  # one full epoch, no repeats

    def test_eval_batches_cover_with_mask(self):
        plan = eval_batches(10, 4)
        assert len(plan) == 3
        assert plan[-1][1] == 2  # last batch valid count
        covered = sorted(set(int(i) for idx, valid in plan for i in idx[:valid]))
        assert covered == list(range(10))


class TestDigitsDatasets:
    """The bundled real-image stand-in (sklearn digits) and its
    class-imbalanced variant (round-4 flagship experiment task)."""

    def test_digits_shapes_and_split(self):
        (xtr, ytr), (xte, yte), info = load_dataset("digits", seed=0)
        assert xtr.shape[1:] == (32, 32, 3) and xtr.dtype == np.uint8
        assert len(xtr) + len(xte) == 1797
        assert info["num_classes"] == 10 and not info["synthetic"]
        # Deterministic in seed.
        (xtr2, _), _, _ = load_dataset("digits", seed=0)
        np.testing.assert_array_equal(xtr, xtr2)

    def test_digits_imb_rare_classes_subsampled(self):
        (xtr, ytr), (xte, yte), info = load_dataset("digits_imb", seed=0)
        (_, ytr_full), (_, yte_full), _ = load_dataset("digits", seed=0)
        counts = np.bincount(ytr, minlength=10)
        full = np.bincount(ytr_full, minlength=10)
        # Common classes untouched, rare classes cut to ~10%.
        np.testing.assert_array_equal(counts[:5], full[:5])
        for c in range(5, 10):
            assert counts[c] <= max(int(round(0.1 * full[c])), 8) + 1, (
                c, counts[c], full[c]
            )
            assert counts[c] >= 8
        # The TEST split stays balanced (identical to the base variant).
        np.testing.assert_array_equal(yte, yte_full)

    def test_digits_seq_is_the_same_real_bytes(self):
        """The FOUND sequence task (round-4 verdict #3): raw scanlines of
        the same scans, same split — no windowing or amplitude shaping."""
        (xtr, ytr), (xte, yte), info = load_dataset("digits_seq", seed=0)
        (xtr_img, ytr_img), _, _ = load_dataset("digits", seed=0)
        assert xtr.shape[1:] == (64, 1) and xtr.dtype == np.float32
        assert xtr.min() >= 0.0 and xtr.max() <= 1.0
        np.testing.assert_array_equal(ytr, ytr_img)  # identical split
        assert not info["synthetic"]
        # The sequence IS the scanline of the image variant's source scan:
        # the 32×32 image upsamples each 8×8 pixel 4×4, so its [::4, ::4]
        # subgrid flattened matches the sequence up to the uint8 quantize.
        sub = xtr_img[0, ::4, ::4, 0].astype(np.float32) / 255.0
        np.testing.assert_allclose(sub.reshape(64), xtr[0, :, 0], atol=0.01)

    def test_digits_seq_imb_mirrors_image_protocol(self):
        (_, ytr), (_, yte), _ = load_dataset("digits_seq_imb", seed=0)
        (_, ytr_img), (_, yte_img), _ = load_dataset("digits_imb", seed=0)
        np.testing.assert_array_equal(ytr, ytr_img)
        np.testing.assert_array_equal(yte, yte_img)


class TestSyntheticSeqHard:
    """The round-4 flagship-experiment task: 15% of samples carry the
    class signal only in the final window (clean labels, structurally
    hard) — the regime where the measured gradient-variance win lives."""

    def test_shapes_and_determinism(self):
        from mercury_tpu.data.cifar import load_dataset

        (xtr, ytr), (xte, yte), info = load_dataset("synthetic_seq_hard",
                                                    seed=0)
        assert xtr.shape == (5000, 32, 16) and xtr.dtype == np.float32
        assert info["num_classes"] == 10
        (xtr2, _), _, _ = load_dataset("synthetic_seq_hard", seed=0)
        np.testing.assert_array_equal(xtr, xtr2)

    def test_hard_minority_is_windowed(self):
        from mercury_tpu.data.cifar import load_dataset

        (xtr, _), _, _ = load_dataset("synthetic_seq_hard", seed=0)
        # Hard samples have ~zero signal outside the final window: their
        # early-timestep variance is pure noise (0.25²), well below the
        # signal+noise variance of easy samples.
        early_var = xtr[:, : 32 - 8].var(axis=(1, 2))
        hard_frac = float((early_var < 0.2).mean())
        assert 0.10 < hard_frac < 0.20, hard_frac
