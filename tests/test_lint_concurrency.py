"""graftlint Layer C: static concurrency rules (GL120–GL125), thread
manifest parity, and the runtime race/leak harness.

Stdlib-heavy by design — the static fixtures never import jax; the
production-module stress tests drive the real writer/pipeline/fleet
objects under the RaceMonitor.
"""

import json
import os
import queue
import textwrap
import threading
import time

import numpy as np
import pytest

from mercury_tpu.lint.concurrency import (
    HOT_THREAD_MODULES,
    THREAD_MANIFEST_SCHEMA,
    default_manifest_path,
    extract_manifest,
    lint_concurrency_source,
    run_concurrency_check,
)
from mercury_tpu.lint.racecheck import (
    InstrumentedQueue,
    RaceMonitor,
    ThreadLeakGuard,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src):
    return lint_concurrency_source(textwrap.dedent(src), "fixture.py")


def _ids(findings):
    return sorted({f.rule_id for f in findings})


# --------------------------------------------------------------- GL120
UNGUARDED_SRC = """
    import threading

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            with self._lock:
                self.count += 1

        def read_side(self):
            return self.count
"""


def test_gl120_unguarded_cross_thread_read():
    findings = _lint(UNGUARDED_SRC)
    assert _ids(findings) == ["GL120"]
    (f,) = findings
    assert "count" in f.message and "_lock" in f.message


def test_gl120_suppressed():
    src = UNGUARDED_SRC.replace(
        "return self.count",
        "return self.count  # graftlint: disable=GL120 -- monotonic "
        "counter, stale read tolerated")
    assert _lint(src) == []


def test_gl120_clean_when_guarded():
    src = UNGUARDED_SRC.replace(
        "return self.count",
        "with self._lock:\n                return self.count")
    assert _lint(src) == []


def test_gl120_no_lock_write_write():
    findings = _lint("""
        import threading

        class W:
            def __init__(self):
                self.n = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self.n += 1

            def trainer_side(self):
                self.n = 5
    """)
    assert _ids(findings) == ["GL120"]
    assert "no lock at all" in findings[0].message


def test_gl120_single_writer_publish_is_clean():
    # whole-object publish + cross-thread read, no lock anywhere:
    # left to the runtime harness by design.
    assert _lint("""
        import threading

        class W:
            def __init__(self):
                self._snap = None

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                snap = self._snap

            def publish(self, x):
                self._snap = (x,)
    """) == []


# --------------------------------------------------------------- GL121
def test_gl121_blocking_put_to_bounded_queue():
    findings = _lint("""
        import queue
        import threading

        class W:
            def __init__(self):
                self._q = queue.Queue(maxsize=4)
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self._q.put(1)
    """)
    assert _ids(findings) == ["GL121"]
    assert "bounded queue" in findings[0].message


def test_gl121_timeout_put_is_clean():
    assert _lint("""
        import queue
        import threading

        class W:
            def __init__(self):
                self._q = queue.Queue(maxsize=4)
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    try:
                        self._q.put(1, timeout=0.1)
                        break
                    except queue.Full:
                        continue
    """) == []


def test_gl121_mixed_get_discipline():
    findings = _lint("""
        import queue

        class W:
            def __init__(self):
                self._q = queue.Queue()

            def a(self):
                return self._q.get()

            def b(self):
                return self._q.get(timeout=1.0)
    """)
    assert _ids(findings) == ["GL121"]
    assert "mixes" in findings[0].message


# --------------------------------------------------------------- GL122
def test_gl122_unjoined_nondaemon_thread():
    findings = _lint("""
        import threading

        class W:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass
    """)
    assert _ids(findings) == ["GL122"]


def test_gl122_joined_or_daemon_is_clean():
    assert _lint("""
        import threading

        class W:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()
                self._d = threading.Thread(target=self._run, daemon=True)
                self._d.start()

            def close(self):
                self._t.join(timeout=30.0)

            def _run(self):
                pass
    """) == []


def test_gl122_join_via_for_alias():
    # for t in self._threads: t.join() must credit _threads
    assert _lint("""
        import threading

        class W:
            def start(self):
                self._threads = [
                    threading.Thread(target=self._run) for _ in range(2)]

            def close(self):
                for t in self._threads:
                    t.join(timeout=1.0)

            def _run(self):
                pass
    """) == []


# --------------------------------------------------------------- GL123
def test_gl123_lock_order_inversion():
    findings = _lint("""
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert _ids(findings) == ["GL123"]
    assert "both orders" in findings[0].message


def test_gl123_consistent_order_is_clean():
    assert _lint("""
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """) == []


def test_gl123_inversion_through_call():
    # one() holds _a and calls helper() which takes _b; two() nests
    # them the other way — the one-level call expansion must see it.
    findings = _lint("""
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    self.helper()

            def helper(self):
                with self._b:
                    pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert _ids(findings) == ["GL123"]


# --------------------------------------------------------------- GL124
def test_gl124_blocking_under_lock():
    findings = _lint("""
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(target=self.poll, daemon=True)

            def poll(self):
                with self._lock:
                    time.sleep(0.5)

            def wait_for(self):
                with self._lock:
                    self._t.join()
    """)
    assert [f.rule_id for f in findings] == ["GL124", "GL124"]


def test_gl124_os_path_join_is_clean():
    assert _lint("""
        import os
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def path(self, d):
                with self._lock:
                    return os.path.join(d, "x")
    """) == []


# ----------------------------------------------------- manifest / GL125
def test_manifest_regen_and_clean_pass(tmp_path):
    manifest = tmp_path / "thread_manifest.json"
    errors, warnings = run_concurrency_check(
        manifest_path=str(manifest), regen=True)
    assert errors == []
    assert any("written" in w for w in warnings)
    doc = json.loads(manifest.read_text())
    assert doc["schema"] == THREAD_MANIFEST_SCHEMA
    # regenerated from the same tree, the committed manifest must match
    committed = json.loads(open(default_manifest_path()).read())
    assert doc == committed
    # and verification against it is clean
    errors, warnings = run_concurrency_check(manifest_path=str(manifest))
    assert errors == [] and warnings == []


def test_manifest_known_fleet():
    doc = extract_manifest(
        [os.path.join(REPO, m) for m in HOT_THREAD_MODULES])
    names = {t["name"] for t in doc["threads"]}
    # mercury-prefetch* / mercury-scorer-*: supervisor restarts append
    # -rN generation suffixes, so the declared names are wildcards.
    assert {"mercury-prefetch*", "mercury-metrics", "mercury-scorer-*",
            "mercury-supervisor", "ckpt-write-*"} <= names
    assert {p["prefix"] for p in doc["pools"]} == {
        "mercury-gather", "mercury-decode"}
    # the checkpoint writer is the fleet's one non-daemon thread
    nondaemon = [t for t in doc["threads"] if not t["daemon"]]
    assert [t["name"] for t in nondaemon] == ["ckpt-write-*"]


def test_gl125_undeclared_thread(tmp_path):
    # a manifest missing the prefetch thread must fail loud on it
    doc = extract_manifest(
        [os.path.join(REPO, m) for m in HOT_THREAD_MODULES])
    doc["threads"] = [t for t in doc["threads"]
                      if t["name"] != "mercury-prefetch*"]
    manifest = tmp_path / "m.json"
    manifest.write_text(json.dumps(doc))
    diff = tmp_path / "diff.txt"
    errors, _ = run_concurrency_check(
        manifest_path=str(manifest), diff_out=str(diff))
    assert any("GL125" in e and "mercury-prefetch" in e for e in errors)
    assert "+ thread" in diff.read_text()


def test_gl125_daemon_flip_and_stale(tmp_path):
    doc = extract_manifest(
        [os.path.join(REPO, m) for m in HOT_THREAD_MODULES])
    for t in doc["threads"]:
        if t["name"] == "mercury-metrics":
            t["daemon"] = False
    doc["threads"].append({"module": "mercury_tpu/gone.py",
                           "class": "Gone", "name": "gone-*",
                           "daemon": True})
    manifest = tmp_path / "m.json"
    manifest.write_text(json.dumps(doc))
    errors, warnings = run_concurrency_check(manifest_path=str(manifest))
    assert any("GL125" in e and "daemon" in e for e in errors)
    assert any("stale" in w for w in warnings)


def test_manifest_missing_raises():
    with pytest.raises(FileNotFoundError):
        run_concurrency_check(manifest_path="/nonexistent/m.json")


def test_hot_modules_statically_clean():
    """The six production threaded subsystems (plus the trainer) pass
    Layer C with the committed manifest — the acceptance gate."""
    errors, warnings = run_concurrency_check()
    assert errors == []
    assert warnings == []


# ------------------------------------------------------------ racecheck
class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.naked = 0
        self.locked = 0

    def bump_naked(self, n):
        for _ in range(n):
            self.naked += 1

    def bump_locked(self, n):
        for _ in range(n):
            with self._lock:
                self.locked += 1


def _hammer(fns):
    threads = [threading.Thread(target=fn) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_racecheck_catches_seeded_race():
    c = _Counter()
    mon = RaceMonitor()
    mon.watch(c, attrs=("naked", "locked"), locks=("_lock",))
    with mon:
        _hammer([lambda: c.bump_naked(3000)] * 2
                + [lambda: c.bump_locked(3000)] * 2)
    races = mon.races()
    assert any(r.attr == "naked" for r in races), races
    assert not any(r.attr == "locked" for r in races), races
    # instrumentation fully reverted
    assert type(c) is _Counter
    assert isinstance(c._lock, type(threading.Lock()))


def test_racecheck_single_thread_is_clean():
    c = _Counter()
    mon = RaceMonitor()
    mon.watch(c, attrs=("naked",), locks=())
    with mon:
        c.bump_naked(1000)
    assert mon.races() == []


def test_instrumented_queue_counts_ops():
    q = InstrumentedQueue(queue.Queue(maxsize=1))
    q.put(1)
    assert q.get() == 1
    with pytest.raises(queue.Empty):
        q.get(timeout=0.01)
    assert q.ops["put"] == 1
    assert q.ops["get"] == 2
    assert q.ops["get_timeout"] == 1


def test_thread_leak_guard():
    guard = ThreadLeakGuard(grace_s=0.2)
    release = threading.Event()
    t = threading.Thread(target=release.wait, daemon=False)
    t.start()
    strays = guard.strays()
    assert [s.name for s in strays] == [t.name]
    with pytest.raises(AssertionError, match="thread leak"):
        guard.check()
    release.set()
    t.join()
    assert guard.strays() == []


# --------------------------------------- production subsystems under TSan-lite
def test_writer_passes_racecheck(tmp_path):
    from mercury_tpu.obs.writer import AsyncMetricWriter, JsonlSink

    w = AsyncMetricWriter([JsonlSink(str(tmp_path))], capacity=8)
    seen = []
    mon = RaceMonitor()
    mon.watch(w, attrs=("dropped", "errors", "observers"),
              locks=("_lock", "_have_work"))
    with mon:
        assert w.add_observer(lambda r: seen.append(r["step"]))
        for step in range(200):
            w.write(step, {"train/loss": float(step)})
        w.flush(timeout=30.0)
        w.close()
    assert mon.races() == []
    assert seen  # the late-registered observer really ran
    assert not w.add_observer(lambda r: None)  # post-close: refused


def test_anomaly_engine_passes_racecheck(tmp_path):
    from mercury_tpu.obs.anomaly import AnomalyEngine

    eng = AnomalyEngine(dump_dir=str(tmp_path), cooldown_steps=0,
                        max_dumps=1000)
    mon = RaceMonitor()
    mon.watch(eng, attrs=("triggers", "trigger_counts", "dumps"),
              locks=("_lock",))

    def drain_side():
        for step in range(50):
            eng.observe_record({"step": step, "time": float(step),
                                "train/loss": float("nan")})

    def trainer_side():
        for step in range(50):
            eng.observe_step_time(step, 0.01)
            eng.take_profile_request()

    with mon:
        _hammer([drain_side, trainer_side])
    assert mon.races() == []
    assert eng.triggers >= 50


def test_prefetch_pipeline_passes_racecheck(rng):
    jax = pytest.importorskip("jax")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mercury_tpu.data.stream import HostStreamSource, PrefetchPipeline
    from mercury_tpu.parallel.mesh import host_cpu_mesh

    x = rng.normal(size=(64, 3, 2)).astype(np.float32)
    sharding = NamedSharding(host_cpu_mesh(1), P())
    src = HostStreamSource(x)
    pipe = PrefetchPipeline(src, (2, 4), sharding, depth=2)
    mon = RaceMonitor()
    mon.watch(pipe, attrs=("total_h2d_bytes", "_exc", "_closed"),
              locks=())
    with mon:
        for step in range(8):
            pipe.push(np.arange(8).reshape(2, 4))
            pipe.pop()
        pipe.close()
    # total_h2d_bytes is worker-written / trainer-read by design
    # (single-writer monotonic counter) — the harness must NOT see an
    # unsynchronized *write/write*, and close() must not leave the
    # worker alive.
    races = mon.races()
    assert not any(r.attr == "_exc" for r in races), races
    assert not pipe._thread.is_alive()


def test_scorer_fleet_close_logs_wedged_and_stays_bounded(monkeypatch):
    """close() must return within its bound and LOG (not hang on) a
    wedged worker. The full fleet needs a model + dataset + config, so
    this drives close() on a skeletal instance — the method touches
    only _closed, _stop (the generation's stop event), and _threads."""
    from mercury_tpu.sampling import scorer_fleet as sf

    logged = []
    monkeypatch.setattr(
        sf._log, "warning", lambda msg, *a: logged.append(msg % a))
    fleet = sf.ScorerFleet.__new__(sf.ScorerFleet)
    fleet._closed = False
    fleet._stop = threading.Event()
    release = threading.Event()
    wedged = threading.Thread(target=release.wait,
                              name="mercury-scorer-0", daemon=True)
    wedged.start()
    fleet._threads = [wedged]
    t0 = time.monotonic()
    fleet.close(timeout=0.2)
    assert time.monotonic() - t0 < 5.0
    assert any("wedged" in m and "mercury-scorer-0" in m for m in logged)
    release.set()
    wedged.join(timeout=10.0)
    # idempotent: a second close is a no-op, bounded or not
    fleet.close(timeout=0.01)


def test_scorer_fleet_stats_has_queue_depth_key():
    import queue as queue_mod

    from mercury_tpu.sampling.scorer_fleet import ScorerFleet

    fleet = ScorerFleet.__new__(ScorerFleet)
    fleet._lock = threading.Lock()
    fleet._rows_scored = 0
    fleet._tick_rows = 0
    fleet._tick_t = time.perf_counter()
    fleet._ages = []
    fleet._ready = queue_mod.Queue(maxsize=2)
    stats = fleet.stats()
    assert "threads/queue_depth/scorer" in stats
    assert stats["threads/queue_depth/scorer"] == 0.0


def test_checkpoint_async_join_times_out(tmp_path, monkeypatch):
    from mercury_tpu.train import checkpoint as ckpt

    wedge = threading.Event()
    save = ckpt._AsyncSave(wedge.wait, name="ckpt-write-test")
    with pytest.raises(TimeoutError, match="did not finish"):
        save.join(timeout=0.2)
    wedge.set()
    save.join(timeout=10.0)  # clean second join after release


def test_host_thread_stats_keys():
    from mercury_tpu.obs.writer import host_thread_stats

    stats = host_thread_stats()
    assert set(stats) == {"threads/alive", "threads/daemon"}
    assert stats["threads/alive"] >= 1.0
    assert stats["threads/daemon"] <= stats["threads/alive"]


def test_writer_queue_depth_counts_pending():
    from mercury_tpu.obs.writer import AsyncMetricWriter

    w = AsyncMetricWriter([], start=False, capacity=8)
    for step in range(3):
        w.write(step, {"train/loss": 0.0})
    assert w.queue_depth() == 3
    w.close()
    assert w.queue_depth() == 0
