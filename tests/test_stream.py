"""Unit tests for the host-streaming input pipeline (``data/stream.py``):
row sources and the PrefetchPipeline driven directly, no Trainer — batch
content/ordering, staging-slab rotation, stall accounting, failure
surfacing, and lifecycle. End-to-end placement parity lives in
``test_data_placement.py``."""

import time

import numpy as np
import pytest

from mercury_tpu.data.stream import (
    HostStreamSource,
    ImageFolderSource,
    PrefetchPipeline,
)
from mercury_tpu.parallel.mesh import host_cpu_mesh


@pytest.fixture(scope="module")
def sharding():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(host_cpu_mesh(1), P())


def make_rows(n=64, row=(3, 2)):
    # row i is wall-to-wall i — any mixup is visible in every element
    return np.broadcast_to(
        np.arange(n, dtype=np.uint8)[:, None, None], (n,) + row
    ).copy()


class TestHostStreamSource:
    def test_gather_matches_fancy_index(self):
        x = make_rows()
        src = HostStreamSource(x)
        gidx = np.array([5, 3, 5, 60], np.int32)
        out = np.empty((4,) + src.row_shape, src.dtype)
        src.gather(gidx, out)
        np.testing.assert_array_equal(out, x[gidx])

    def test_decode_workers_equivalent(self):
        x = make_rows()
        gidx = np.arange(63, -1, -1, dtype=np.int32)
        serial = np.empty_like(x)
        threaded = np.empty_like(x)
        HostStreamSource(x).gather(gidx, serial)
        src = HostStreamSource(x, decode_workers=3)
        try:
            src.gather(gidx, threaded)
        finally:
            src.close()
        np.testing.assert_array_equal(serial, threaded)

    def test_memmap_rows(self, tmp_path):
        x = make_rows(16)
        p = tmp_path / "rows.bin"
        x.tofile(p)
        mm = np.memmap(p, dtype=np.uint8, mode="r", shape=x.shape)
        src = HostStreamSource(mm)
        out = np.empty((2,) + src.row_shape, src.dtype)
        src.gather(np.array([1, 15]), out)
        np.testing.assert_array_equal(out, x[[1, 15]])

    def test_scalar_rejected(self):
        with pytest.raises(ValueError, match="array"):
            HostStreamSource(3)


class TestImageFolderSource:
    @pytest.fixture()
    def folder(self, tmp_path):
        Image = pytest.importorskip("PIL.Image")
        for cls, shade in (("cat", 40), ("dog", 200)):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                arr = np.full((8, 8, 3), shade + i, np.uint8)
                Image.fromarray(arr).save(d / f"{i}.png")
        return tmp_path

    def test_matches_eager_loader(self, folder):
        from mercury_tpu.data.imagefolder import load_image_folder

        eager_x, eager_y, classes = load_image_folder(str(folder), 8)
        src = ImageFolderSource(str(folder), image_size=8)
        assert len(src) == 4
        assert src.classes == classes
        np.testing.assert_array_equal(src.labels, eager_y)
        out = np.empty((4,) + src.row_shape, src.dtype)
        src.gather(np.arange(4), out)
        np.testing.assert_array_equal(out, eager_x)

    def test_decode_workers(self, folder):
        src = ImageFolderSource(str(folder), image_size=8, decode_workers=2)
        try:
            out = np.empty((2,) + src.row_shape, src.dtype)
            src.gather(np.array([3, 0]), out)
            assert out[0, 0, 0, 0] == 201  # dog/1.png
            assert out[1, 0, 0, 0] == 40   # cat/0.png
        finally:
            src.close()

    def test_image_size_mandatory(self, folder):
        with pytest.raises(ValueError, match="image_size"):
            ImageFolderSource(str(folder), image_size=None)


class TestPrefetchPipeline:
    def _pipe(self, sharding, x=None, depth=2, **kw):
        x = make_rows() if x is None else x
        src = HostStreamSource(x)
        return x, PrefetchPipeline(src, (1, 4), sharding, depth=depth, **kw)

    def test_batches_in_push_order(self, sharding):
        x, pipe = self._pipe(sharding)
        try:
            sels = [np.array([[0, 1, 2, 3]]), np.array([[9, 8, 7, 6]]),
                    np.array([[4, 4, 4, 4]])]
            for s in sels:
                pipe.push(s)
            for s in sels:
                got = np.asarray(pipe.pop())
                np.testing.assert_array_equal(got, x[s])
            assert pipe.pops == 3
        finally:
            pipe.close()

    def test_slab_rotation_no_corruption(self, sharding):
        # More batches than depth+1 slabs: every popped batch must still
        # hold ITS rows, not a later gather's overwrite.
        x, pipe = self._pipe(sharding, depth=2)
        try:
            sels = [np.full((1, 4), i, np.int32) for i in range(8)]
            batches = []
            for s in sels[: pipe.depth]:
                pipe.push(s)
            for i in range(8):
                batches.append(pipe.pop())
                if i + pipe.depth < 8:
                    pipe.push(sels[i + pipe.depth])
            for i, b in enumerate(batches):
                np.testing.assert_array_equal(np.asarray(b), x[sels[i]])
        finally:
            pipe.close()

    def test_stall_accounting(self, sharding):
        class SlowSource:
            row_shape, dtype = (3, 2), np.dtype(np.uint8)

            def gather(self, gidx, out):
                time.sleep(0.05)
                out[: len(gidx)] = 1

        pipe = PrefetchPipeline(SlowSource(), (1, 4), sharding, depth=2)
        try:
            t0 = time.monotonic()
            pipe.push(np.zeros((1, 4), np.int32))
            pipe.pop()  # must wait through the slow gather
            assert time.monotonic() - t0 >= 0.05
            assert pipe.total_wait_s >= 0.05
            # the wait is host-side gather → fully input-attributable
            assert pipe.total_stall_s >= 0.04
            stats = pipe.stats()
            assert stats["data/stall_s"] >= 0.04
            assert stats["data/h2d_bytes"] == 1 * 4 * 3 * 2
            # interval semantics: a second call reports only new stall
            assert pipe.stats()["data/stall_s"] == 0.0
        finally:
            pipe.close()

    def test_worker_failure_surfaces_on_pop(self, sharding):
        class FailingSource:
            row_shape, dtype = (3, 2), np.dtype(np.uint8)

            def gather(self, gidx, out):
                raise RuntimeError("disk on fire")

        pipe = PrefetchPipeline(FailingSource(), (1, 4), sharding, depth=2)
        try:
            pipe.push(np.zeros((1, 4), np.int32))
            with pytest.raises(RuntimeError, match="prefetch worker died"):
                pipe.pop()
        finally:
            pipe.close()

    def test_pop_timeout_without_push(self, sharding):
        _, pipe = self._pipe(sharding, pop_timeout_s=0.2)
        try:
            with pytest.raises(TimeoutError, match="push"):
                pipe.pop()
        finally:
            pipe.close()

    def test_reset_discards_inflight(self, sharding):
        x, pipe = self._pipe(sharding)
        try:
            pipe.push(np.array([[0, 1, 2, 3]]))
            pipe.pop()
            pipe.push(np.array([[9, 9, 9, 9]]))
            time.sleep(0.2)  # let the worker commit it
            pipe.reset()
            pipe.push(np.array([[5, 6, 7, 8]]))
            got = np.asarray(pipe.pop())
            np.testing.assert_array_equal(got, x[np.array([[5, 6, 7, 8]])])
        finally:
            pipe.close()

    def test_close_idempotent_push_after_close_raises(self, sharding):
        _, pipe = self._pipe(sharding)
        pipe.close()
        pipe.close()
        with pytest.raises(RuntimeError, match="closed"):
            pipe.push(np.zeros((1, 4), np.int32))

    def test_bad_depth_rejected(self, sharding):
        with pytest.raises(ValueError, match="depth"):
            PrefetchPipeline(HostStreamSource(make_rows()), (1, 4),
                             sharding, depth=0)
