"""Tensor-parallel (GSPMD) tests: the Megatron sharding layout for the
Transformer must (a) physically shard the block matmul weights, (b) leave
forward/gradients numerically identical to the unsharded model — XLA
inserts the collectives — and (c) compose with data parallelism on a 2-D
(data × model) mesh. Beyond-parity extension (SURVEY.md §2.5: the
reference's only strategy is data parallelism)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mercury_tpu.models import TransformerClassifier
from mercury_tpu.parallel.tensor import shard_params_tp, transformer_tp_shardings
from mercury_tpu.sampling.importance import per_sample_loss

pytestmark = pytest.mark.slow  # parallelism-matrix compile cost blows the tier-1 budget

T, F, C, D = 32, 12, 5, 32


@pytest.fixture(scope="module")
def setup():
    model = TransformerClassifier(num_classes=C, d_model=D, num_heads=4,
                                  num_layers=2, max_len=T)
    x = jax.random.normal(jax.random.key(0), (8, T, F), jnp.float32)
    y = jnp.arange(8) % C
    params = model.init(jax.random.key(1), x, train=False)["params"]
    return model, x, y, params


def model_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("model",))


class TestShardingLayout:
    def test_block_kernels_are_split(self, setup):
        model, x, y, params = setup
        mesh = model_mesh(4)
        sharded = shard_params_tp(params, mesh)
        q = sharded["block0"]["query"]["kernel"]
        assert q.shape == (D, D)
        # Column-parallel: each device holds one head group [D, D/4].
        assert q.addressable_shards[0].data.shape == (D, D // 4)
        down = sharded["block1"]["Dense_1"]["kernel"]
        # Row-parallel: input features split.
        assert down.addressable_shards[0].data.shape == (down.shape[0] // 4,
                                                         down.shape[1])
        # Non-block params replicated.
        head = sharded["head"]["kernel"]
        assert head.addressable_shards[0].data.shape == head.shape

    def test_specs_cover_whole_tree(self, setup):
        _, _, _, params = setup
        mesh = model_mesh(4)
        shardings = transformer_tp_shardings(params, mesh)
        assert jax.tree_util.tree_structure(shardings) == \
            jax.tree_util.tree_structure(params)
        assert all(isinstance(s, NamedSharding)
                   for s in jax.tree_util.tree_leaves(shardings))


class TestNumericalEquivalence:
    def test_forward_matches_unsharded(self, setup):
        model, x, y, params = setup
        mesh = model_mesh(4)
        ref = model.apply({"params": params}, x, train=False)
        sharded = shard_params_tp(params, mesh)
        out = jax.jit(
            lambda p, x: model.apply({"params": p}, x, train=False)
        )(sharded, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_train_step_matches_unsharded(self, setup):
        """One SGD step with TP-sharded params == unsharded step: GSPMD's
        inserted collectives reproduce the dense gradients."""
        model, x, y, params = setup
        tx = optax.sgd(0.1)

        def step(p, x, y):
            def loss_fn(p):
                logits = model.apply({"params": p}, x, train=True)
                return jnp.mean(per_sample_loss(logits, y))

            loss, grads = jax.value_and_grad(loss_fn)(p)
            updates, _ = tx.update(grads, tx.init(p), p)
            return optax.apply_updates(p, updates), loss

        p_ref, ref_loss = jax.jit(step)(params, x, y)

        mesh = model_mesh(4)
        sharded = shard_params_tp(params, mesh)
        p_tp, tp_loss = jax.jit(step)(sharded, x, y)
        np.testing.assert_allclose(float(tp_loss), float(ref_loss), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(p_tp),
                        jax.tree_util.tree_leaves(p_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        # The updated params keep the TP layout (no silent gather-back).
        q = p_tp["block0"]["query"]["kernel"]
        assert q.addressable_shards[0].data.shape == (D, D // 4)

    def test_megatron_collective_count(self, setup):
        """Structural pin: the head-aligned q/k/v split means the compiled
        forward needs exactly 2 all-reduces per block (attention proj +
        MLP down) and NO all-gather/reshard — the Megatron pattern."""
        import re

        model, x, y, params = setup
        mesh = model_mesh(4)
        sharded = shard_params_tp(params, mesh)
        hlo = jax.jit(
            lambda p, x: model.apply({"params": p}, x, train=False)
        ).lower(sharded, x).compile().as_text()
        n_blocks = 2
        assert len(re.findall(r"all-reduce(?:-start)?\(", hlo)) == 2 * n_blocks
        assert len(re.findall(r"all-gather(?:-start)?\(", hlo)) == 0

    def test_dp_tp_2d_mesh(self, setup):
        """data × model mesh: batch sharded over 'data', weights over
        'model' — forward matches unsharded."""
        model, x, y, params = setup
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "model"))
        ref = model.apply({"params": params}, x, train=False)
        sharded = shard_params_tp(params, mesh)
        xs = jax.device_put(x, NamedSharding(mesh, P("data")))
        out = jax.jit(
            lambda p, x: model.apply({"params": p}, x, train=False)
        )(sharded, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestMercuryISWithTP:
    """The flagship importance-sampled step composed with tensor
    parallelism: Trainer(tensor_parallel=2) runs the SAME fused IS program
    (scoring forward, EMA, draw, reweighted backward, stat psum) with
    every transformer matmul Megatron-sharded over the model axis —
    numerically equal to the unsharded IS step."""

    def _cfg(self, **kw):
        from mercury_tpu.config import TrainConfig

        base = dict(model="transformer", dataset="synthetic_seq",
                    augmentation="none", world_size=2, batch_size=4,
                    presample_batches=2, steps_per_epoch=3, num_epochs=1,
                    eval_every=0, log_every=0, compute_dtype="float32",
                    seed=0, sync_importance_stats=True)
        base.update(kw)
        return TrainConfig(**base)

    def test_tp_is_step_matches_unsharded(self):
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        base = Trainer(self._cfg(), mesh=host_cpu_mesh(2))
        tp = Trainer(self._cfg(tensor_parallel=2))
        for _ in range(3):
            base.state, mb = base.train_step(
                base.state, base.dataset.x_train, base.dataset.y_train,
                base.dataset.shard_indices)
            tp.state, mt = tp.train_step(
                tp.state, tp.dataset.x_train, tp.dataset.y_train,
                tp.dataset.shard_indices)
            np.testing.assert_allclose(float(mt["train/loss"]),
                                       float(mb["train/loss"]), rtol=1e-4)
        # Params: absolute tolerance only — TP reassociates fp32 reductions
        # and Adam's m/(sqrt(v)+eps) amplifies last-ulp differences on
        # near-zero second moments (per-step losses are pinned above).
        for a, b in zip(jax.tree_util.tree_leaves(base.state.params),
                        jax.tree_util.tree_leaves(tp.state.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=2e-3)

    def test_tp_layout_stable_across_steps(self):
        """Params AND optimizer moments stay Megatron-sharded after every
        step (out_shardings pin) — GSPMD must not re-replicate them."""
        from mercury_tpu.train.trainer import Trainer

        tp = Trainer(self._cfg(tensor_parallel=2))
        param_specs = {str(l.sharding.spec)
                       for l in jax.tree_util.tree_leaves(tp.state.params)}
        assert any("model" in s for s in param_specs), param_specs
        before = [l.sharding for l in
                  jax.tree_util.tree_leaves(tp.state.params)]
        for _ in range(2):
            tp.state, _ = tp.train_step(
                tp.state, tp.dataset.x_train, tp.dataset.y_train,
                tp.dataset.shard_indices)
        after = [l.sharding for l in
                 jax.tree_util.tree_leaves(tp.state.params)]
        assert before == after
        opt_specs = {str(l.sharding.spec)
                     for l in jax.tree_util.tree_leaves(tp.state.opt_state)
                     if hasattr(l, "sharding")}
        assert any("model" in s for s in opt_specs), opt_specs

    def test_tp_scan_and_pipelined(self):
        from mercury_tpu.train.trainer import Trainer

        sc = Trainer(self._cfg(tensor_parallel=2, scan_steps=3))
        sc.state, m = sc.train_step_many(
            sc.state, sc.dataset.x_train, sc.dataset.y_train,
            sc.dataset.shard_indices)
        assert m["train/loss"].shape == (3,)
        assert np.isfinite(np.asarray(m["train/loss"])).all()

        pl = Trainer(self._cfg(tensor_parallel=2, pipelined_scoring=True))
        pl.state, m = pl.train_step(
            pl.state, pl.dataset.x_train, pl.dataset.y_train,
            pl.dataset.shard_indices)
        assert np.isfinite(float(m["train/loss"]))

    def test_tp_eval_runs(self):
        from mercury_tpu.train.trainer import Trainer

        tp = Trainer(self._cfg(tensor_parallel=2))
        out = tp.evaluate()
        assert set(out) == {"train/eval_loss", "train/eval_acc",
                            "test/eval_loss", "test/eval_acc"}

    def test_tp_rejects_bad_compositions(self):
        from mercury_tpu.train.trainer import Trainer

        with pytest.raises(ValueError, match="zero_sharding"):
            Trainer(self._cfg(tensor_parallel=2, zero_sharding=True))
        # int8 × TP is no longer a rejection: the per-leaf compressed
        # pmean composes (test_compressed_collective.py::
        # TestCompressedPmeanND::test_int8_composes_with_tp).
        with pytest.raises(ValueError, match="transformer"):
            Trainer(self._cfg(tensor_parallel=2, model="smallcnn",
                              dataset="synthetic", augmentation="noniid"))
        with pytest.raises(ValueError, match="num_heads"):
            Trainer(self._cfg(tensor_parallel=3, world_size=1))

    def test_tp_checkpoint_resume_keeps_layout(self, tmp_path):
        """Save → restore into a fresh TP trainer: the Megatron layout is
        re-committed on restore (no replicated detour, jit cache hit) and
        training continues deterministically."""
        from mercury_tpu.train import restore_checkpoint, save_checkpoint
        from mercury_tpu.train.trainer import Trainer

        a = Trainer(self._cfg(tensor_parallel=2))
        losses_a = []
        for _ in range(3):
            a.state, m = a.train_step(
                a.state, a.dataset.x_train, a.dataset.y_train,
                a.dataset.shard_indices)
            losses_a.append(float(m["train/loss"]))

        b = Trainer(self._cfg(tensor_parallel=2))
        b.state, _ = b.train_step(
            b.state, b.dataset.x_train, b.dataset.y_train,
            b.dataset.shard_indices)
        save_checkpoint(str(tmp_path), b.state, 1)

        c = Trainer(self._cfg(tensor_parallel=2,
                              checkpoint_dir=str(tmp_path)))
        c.restore()
        specs = {str(l.sharding.spec)
                 for l in jax.tree_util.tree_leaves(c.state.params)}
        assert any("model" in s for s in specs), specs
        losses_c = []
        for _ in range(2):
            c.state, m = c.train_step(
                c.state, c.dataset.x_train, c.dataset.y_train,
                c.dataset.shard_indices)
            losses_c.append(float(m["train/loss"]))
        np.testing.assert_allclose(losses_c, losses_a[1:], rtol=1e-4)


class TestTPCadence:
    def test_tp_composes_with_score_cadence(self):
        """score_refresh_every through the dp×tp step: the CachedPool
        state field must appear in the TP out-shardings pin (sharded over
        data, untouched by GSPMD's model-axis partitioning)."""
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="transformer", dataset="synthetic_seq",
            augmentation="none", world_size=2, tensor_parallel=2,
            batch_size=4, presample_batches=2, steps_per_epoch=4,
            num_epochs=1, eval_every=0, log_every=0,
            compute_dtype="float32", seed=0, score_refresh_every=2,
        )
        tr = Trainer(cfg)
        for _ in range(4):
            tr.state, m = tr.train_step(
                tr.state, tr.dataset.x_train, tr.dataset.y_train,
                tr.dataset.shard_indices)
            assert np.isfinite(float(m["train/loss"]))
        assert int(tr.state.step) == 4
        # Refreshes at steps 0 and 2 only.
        assert int(np.asarray(tr.state.ema.count).max()) == 2
        probs = np.asarray(tr.state.cached_pool.probs)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)
