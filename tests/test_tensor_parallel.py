"""Tensor-parallel (GSPMD) tests: the Megatron sharding layout for the
Transformer must (a) physically shard the block matmul weights, (b) leave
forward/gradients numerically identical to the unsharded model — XLA
inserts the collectives — and (c) compose with data parallelism on a 2-D
(data × model) mesh. Beyond-parity extension (SURVEY.md §2.5: the
reference's only strategy is data parallelism)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mercury_tpu.models import TransformerClassifier
from mercury_tpu.parallel.tensor import shard_params_tp, transformer_tp_shardings
from mercury_tpu.sampling.importance import per_sample_loss

T, F, C, D = 32, 12, 5, 32


@pytest.fixture(scope="module")
def setup():
    model = TransformerClassifier(num_classes=C, d_model=D, num_heads=4,
                                  num_layers=2, max_len=T)
    x = jax.random.normal(jax.random.key(0), (8, T, F), jnp.float32)
    y = jnp.arange(8) % C
    params = model.init(jax.random.key(1), x, train=False)["params"]
    return model, x, y, params


def model_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("model",))


class TestShardingLayout:
    def test_block_kernels_are_split(self, setup):
        model, x, y, params = setup
        mesh = model_mesh(4)
        sharded = shard_params_tp(params, mesh)
        q = sharded["block0"]["query"]["kernel"]
        assert q.shape == (D, D)
        # Column-parallel: each device holds one head group [D, D/4].
        assert q.addressable_shards[0].data.shape == (D, D // 4)
        down = sharded["block1"]["Dense_1"]["kernel"]
        # Row-parallel: input features split.
        assert down.addressable_shards[0].data.shape == (down.shape[0] // 4,
                                                         down.shape[1])
        # Non-block params replicated.
        head = sharded["head"]["kernel"]
        assert head.addressable_shards[0].data.shape == head.shape

    def test_specs_cover_whole_tree(self, setup):
        _, _, _, params = setup
        mesh = model_mesh(4)
        shardings = transformer_tp_shardings(params, mesh)
        assert jax.tree_util.tree_structure(shardings) == \
            jax.tree_util.tree_structure(params)
        assert all(isinstance(s, NamedSharding)
                   for s in jax.tree_util.tree_leaves(shardings))


class TestNumericalEquivalence:
    def test_forward_matches_unsharded(self, setup):
        model, x, y, params = setup
        mesh = model_mesh(4)
        ref = model.apply({"params": params}, x, train=False)
        sharded = shard_params_tp(params, mesh)
        out = jax.jit(
            lambda p, x: model.apply({"params": p}, x, train=False)
        )(sharded, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_train_step_matches_unsharded(self, setup):
        """One SGD step with TP-sharded params == unsharded step: GSPMD's
        inserted collectives reproduce the dense gradients."""
        model, x, y, params = setup
        tx = optax.sgd(0.1)

        def step(p, x, y):
            def loss_fn(p):
                logits = model.apply({"params": p}, x, train=True)
                return jnp.mean(per_sample_loss(logits, y))

            loss, grads = jax.value_and_grad(loss_fn)(p)
            updates, _ = tx.update(grads, tx.init(p), p)
            return optax.apply_updates(p, updates), loss

        p_ref, ref_loss = jax.jit(step)(params, x, y)

        mesh = model_mesh(4)
        sharded = shard_params_tp(params, mesh)
        p_tp, tp_loss = jax.jit(step)(sharded, x, y)
        np.testing.assert_allclose(float(tp_loss), float(ref_loss), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(p_tp),
                        jax.tree_util.tree_leaves(p_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        # The updated params keep the TP layout (no silent gather-back).
        q = p_tp["block0"]["query"]["kernel"]
        assert q.addressable_shards[0].data.shape == (D, D // 4)

    def test_megatron_collective_count(self, setup):
        """Structural pin: the head-aligned q/k/v split means the compiled
        forward needs exactly 2 all-reduces per block (attention proj +
        MLP down) and NO all-gather/reshard — the Megatron pattern."""
        import re

        model, x, y, params = setup
        mesh = model_mesh(4)
        sharded = shard_params_tp(params, mesh)
        hlo = jax.jit(
            lambda p, x: model.apply({"params": p}, x, train=False)
        ).lower(sharded, x).compile().as_text()
        n_blocks = 2
        assert len(re.findall(r"all-reduce(?:-start)?\(", hlo)) == 2 * n_blocks
        assert len(re.findall(r"all-gather(?:-start)?\(", hlo)) == 0

    def test_dp_tp_2d_mesh(self, setup):
        """data × model mesh: batch sharded over 'data', weights over
        'model' — forward matches unsharded."""
        model, x, y, params = setup
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "model"))
        ref = model.apply({"params": params}, x, train=False)
        sharded = shard_params_tp(params, mesh)
        xs = jax.device_put(x, NamedSharding(mesh, P("data")))
        out = jax.jit(
            lambda p, x: model.apply({"params": p}, x, train=False)
        )(sharded, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
