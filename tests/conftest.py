"""Test harness: force an 8-device virtual CPU platform so psum/sharding
logic is exercised without a TPU pod (SURVEY.md §4's multi-device test
strategy).

Note: this environment's sitecustomize registers a remote-TPU ("axon") PJRT
backend at interpreter start and pins ``JAX_PLATFORMS=axon``, so an env-var
``setdefault`` is not enough — we must set the XLA host-device flag before
backend init and override the platform via ``jax.config``."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from mercury_tpu.platform import select_cpu_if_requested  # noqa: E402

select_cpu_if_requested()

import jax  # noqa: E402

# Persistent compilation cache: the suite's cost is dominated by XLA CPU
# compiles of the fused train-step programs (ResNet-50, MobileNetV2, scanned
# chunks — 10+ minutes cold). Cached, a rerun skips recompilation entirely.
_cache_dir = os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
