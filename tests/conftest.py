"""Test harness: force an 8-device virtual CPU platform so psum/sharding
logic is exercised without a TPU pod (SURVEY.md §4's multi-device test
strategy).

Note: this environment's sitecustomize registers a remote-TPU ("axon") PJRT
backend at interpreter start and pins ``JAX_PLATFORMS=axon``, so an env-var
``setdefault`` is not enough — we must set the XLA host-device flag before
backend init and override the platform via ``jax.config``."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from mercury_tpu.platform import select_cpu_if_requested  # noqa: E402

select_cpu_if_requested()

import jax  # noqa: E402

# Persistent compilation cache: the suite's cost is dominated by XLA CPU
# compiles of the fused train-step programs (ResNet-50, MobileNetV2, scanned
# chunks — 10+ minutes cold). Cached, a rerun skips recompilation entirely.
_cache_dir = os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from mercury_tpu.lint.racecheck import ThreadLeakGuard  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _no_thread_leaks(request):
    """Tier-1-wide thread-leak guard (graftlint Layer C's runtime side):
    any test that starts a non-daemon thread must join it before
    returning — a leaked writer/prefetch/checkpoint thread wedges the
    whole pytest process at exit and poisons every later test's thread
    census. Opt out with ``@pytest.mark.thread_leak_ok`` (the slow
    distributed matrix parks helpers across tests by design)."""
    if request.node.get_closest_marker("thread_leak_ok") is not None:
        yield
        return
    guard = ThreadLeakGuard(grace_s=5.0)
    yield
    strays = guard.strays()
    if strays:
        names = ", ".join(sorted(t.name for t in strays))
        pytest.fail(
            f"test leaked non-daemon thread(s) still alive after the "
            f"5s grace join: {names} — close()/join() them, or mark "
            f"the test thread_leak_ok", pytrace=False)
