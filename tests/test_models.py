"""Model zoo tests: layer shapes per the reference architecture
(``pytorch_model.py:67-101``), parameter counts, gradient flow, and the
factory (SURVEY.md §4: "numerical cross-checks of Flax ResNet-18 vs. the
reference architecture (layer shapes)")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mercury_tpu.models import (
    BiLSTMAttention,
    create_model,
)

pytestmark = pytest.mark.slow  # parallelism-matrix compile cost blows the tier-1 budget


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def init_model(model, shape=(2, 32, 32, 3)):
    x = jnp.zeros(shape, jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    return variables, x


class TestResNet:
    def test_resnet18_output_shape(self):
        model = create_model("resnet18", num_classes=10, compute_dtype="float32")
        variables, x = init_model(model)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32

    def test_resnet18_param_count_matches_reference_arch(self):
        """CIFAR ResNet-18 (3×3 stem, 4 stages 64/128/256/512, 10-way head)
        has 11,173,962 trainable params — the standard count for the
        architecture at ``pytorch_model.py:67-101``."""
        model = create_model("resnet18", num_classes=10)
        variables, _ = init_model(model)
        assert param_count(variables["params"]) == 11_173_962

    def test_resnet50_uses_bottleneck_expansion(self):
        model = create_model("resnet50", num_classes=10, compute_dtype="float32")
        variables, x = init_model(model)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
        # Bottleneck expansion 4 → final Dense sees 2048 features.
        dense = [k for k in variables["params"] if k.startswith("Dense")]
        assert variables["params"][dense[0]]["kernel"].shape == (2048, 10)

    @pytest.mark.parametrize("name", ["resnet34"])
    def test_other_depths_forward(self, name):
        model = create_model(name, num_classes=7, compute_dtype="float32")
        variables, x = init_model(model)
        assert model.apply(variables, x, train=False).shape == (2, 7)

    def test_gradients_flow(self):
        model = create_model("resnet18", num_classes=10, compute_dtype="float32")
        variables, _ = init_model(model)
        x = jnp.asarray(np.random.default_rng(0).normal(0, 1, (2, 32, 32, 3)),
                        jnp.float32)

        def loss(params):
            logits, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return jnp.mean(logits**2)

        grads = jax.grad(loss)(variables["params"])
        norms = [float(jnp.abs(g).sum()) for g in jax.tree_util.tree_leaves(grads)]
        assert all(np.isfinite(n) for n in norms)
        assert sum(n > 0 for n in norms) > len(norms) * 0.5

    def test_bf16_compute_fp32_logits(self):
        model = create_model("resnet18", num_classes=10, compute_dtype="bfloat16")
        variables, x = init_model(model)
        out = model.apply(variables, x, train=False)
        assert out.dtype == jnp.float32  # logits cast back for stable loss


class TestVGG:
    def test_vgg11_forward(self):
        model = create_model("vgg11", num_classes=10, compute_dtype="float32")
        variables, x = init_model(model)
        assert model.apply(variables, x, train=False).shape == (2, 10)

    def test_vgg_accepts_3_channel_input(self):
        """The reference VGG is hardwired to 1-channel input
        (``pytorch_model.py:119``) — a documented defect we fix: 3-channel
        CIFAR input must work out of the box."""
        model = create_model("vgg13", num_classes=10, compute_dtype="float32")
        variables, x = init_model(model, (1, 32, 32, 3))
        assert model.apply(variables, x, train=False).shape == (1, 10)

    def test_vgg16_structure(self):
        model = create_model("vgg16", num_classes=100, compute_dtype="float32")
        variables, x = init_model(model)
        convs = [k for k in variables["params"] if k.startswith("Conv")]
        assert len(convs) == 13  # VGG-16: 13 conv layers


class TestMobileNetV2:
    def test_forward_shape(self):
        model = create_model("mobilenetv2", num_classes=10, compute_dtype="float32")
        variables, x = init_model(model)
        assert model.apply(variables, x, train=False).shape == (2, 10)

    def test_cifar_stem_keeps_resolution(self):
        """CIFAR variant: stride-1 stem + first two down-stages at stride 1
        → only 3 downsamples on 32×32 (final 4×4 map), not the ImageNet 32×."""
        model = create_model("mobilenetv2", num_classes=10, compute_dtype="float32")
        variables, x = init_model(model)
        # Param count sanity: ~2.2-2.4M for width 1.0 @ 10 classes.
        n = param_count(variables["params"])
        assert 2_000_000 < n < 2_600_000


class TestBiLSTMAttention:
    def test_forward_with_lengths(self):
        model = BiLSTMAttention(num_classes=5, hidden_dim=16, attention_dim=8,
                                mlp_dim=16)
        x = jnp.zeros((3, 12, 20), jnp.float32)  # [B, T, F]
        lengths = jnp.asarray([12, 5, 1], jnp.int32)
        variables = model.init(jax.random.key(0), x, lengths, train=False)
        out = model.apply(variables, x, lengths, train=False)
        assert out.shape == (3, 5)

    def test_mask_excludes_padding(self):
        """Changing padded positions must not change the output when lengths
        mask them (the per-sequence mask of ``pytorch_model.py:189-198``)."""
        model = BiLSTMAttention(num_classes=4, hidden_dim=8, attention_dim=8,
                                mlp_dim=8)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(0, 1, (2, 10, 6)), jnp.float32)
        lengths = jnp.asarray([6, 10], jnp.int32)
        variables = model.init(jax.random.key(0), x, lengths, train=False)
        out1 = model.apply(variables, x, lengths, train=False)
        x2 = x.at[0, 6:].set(99.0)  # only padding of sequence 0 changes
        out2 = model.apply(variables, x2, lengths, train=False)
        np.testing.assert_allclose(np.asarray(out1[0]), np.asarray(out2[0]),
                                   atol=1e-5)

    def test_gradients_flow(self):
        model = BiLSTMAttention(num_classes=3, hidden_dim=8, attention_dim=8,
                                mlp_dim=8)
        x = jnp.ones((2, 6, 4), jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)

        def loss(params):
            return jnp.sum(model.apply({"params": params}, x, train=True) ** 2)

        grads = jax.grad(loss)(variables["params"])
        total = sum(float(jnp.abs(g).sum()) for g in jax.tree_util.tree_leaves(grads))
        assert np.isfinite(total) and total > 0


class TestFactory:
    def test_unknown_name_raises(self):
        with pytest.raises(ValueError, match="unknown model"):
            create_model("alexnet")

    @pytest.mark.parametrize("name", ["resnet18", "vgg11", "mobilenetv2", "smallcnn"])
    def test_bn_axis_threads_through(self, name):
        model = create_model(name, bn_axis_name="data", compute_dtype="float32")
        # Init outside a mesh must still work (axis unused at init).
        variables, x = init_model(model, (1, 32, 32, 3))
        assert "batch_stats" in variables


class TestRemat:
    """Activation rematerialization (``TransformerClassifier(remat=True)``,
    ``config.remat``): identical params, loss, and gradients — only the
    backward-pass memory/FLOP tradeoff changes."""

    def test_remat_grads_match_dense(self):
        import jax.numpy as jnp

        from mercury_tpu.models import TransformerClassifier
        from mercury_tpu.sampling.importance import per_sample_loss

        kw = dict(num_classes=5, d_model=32, num_heads=2, num_layers=2,
                  max_len=16)
        x = jax.random.normal(jax.random.key(0), (4, 16, 8), jnp.float32)
        y = jnp.arange(4) % 5
        dense = TransformerClassifier(**kw)
        remat = TransformerClassifier(remat=True, **kw)
        params = dense.init(jax.random.key(1), x, train=False)["params"]

        def loss_fn(model):
            def f(p):
                logits = model.apply({"params": p}, x, train=True)
                return jnp.mean(per_sample_loss(logits, y))
            return f

        ld, gd = jax.value_and_grad(loss_fn(dense))(params)
        lr, gr = jax.value_and_grad(loss_fn(remat))(params)
        assert jax.tree_util.tree_structure(gd) == \
            jax.tree_util.tree_structure(gr)
        np.testing.assert_allclose(float(ld), float(lr), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(gd),
                        jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_remat_trains_through_mercury_step(self):
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="transformer", dataset="synthetic_seq", augmentation="none",
            world_size=4, batch_size=8, presample_batches=2, num_epochs=1,
            steps_per_epoch=5, eval_every=0, log_every=0, remat=True,
            compute_dtype="float32", seed=0,
        )
        tr = Trainer(cfg, mesh=host_cpu_mesh(4))
        for _ in range(5):
            tr.state, m = tr.train_step(
                tr.state, tr.dataset.x_train, tr.dataset.y_train,
                tr.dataset.shard_indices)
            assert np.isfinite(float(m["train/loss"]))


class TestViT:
    """ViT mode: patch_size patchifies 4-D image input into tokens, so
    the whole transformer stack — and its TP/PP machinery, which shards
    the blocks — applies unchanged to the image datasets."""

    def test_patchify_shapes_and_learning(self):
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="vit", dataset="synthetic", world_size=4, batch_size=8,
            presample_batches=2, steps_per_epoch=40, num_epochs=1,
            eval_every=0, log_every=0, compute_dtype="float32", seed=0,
        )
        tr = Trainer(cfg, mesh=host_cpu_mesh(4))
        first = None
        for _ in range(40):
            tr.state, m = tr.train_step(
                tr.state, tr._step_x, tr._step_y, tr.dataset.shard_indices)
            if first is None:
                first = float(m["train/loss"])
        assert float(m["train/loss"]) < first, (float(m["train/loss"]), first)
        acc = tr.evaluate(include_train=False)["test/eval_acc"]
        assert acc > 0.15, acc  # 10 classes, chance 0.1; 40 steps is short

    def test_vit_tp_matches_unsharded(self):
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        kw = dict(model="vit", dataset="synthetic", world_size=2,
                  batch_size=4, presample_batches=2, steps_per_epoch=2,
                  num_epochs=1, eval_every=0, log_every=0,
                  compute_dtype="float32", seed=0)
        base = Trainer(TrainConfig(**kw), mesh=host_cpu_mesh(2))
        tp = Trainer(TrainConfig(**kw, tensor_parallel=2))
        for _ in range(2):
            base.state, mb = base.train_step(
                base.state, base._step_x, base._step_y,
                base.dataset.shard_indices)
            tp.state, mt = tp.train_step(
                tp.state, tp._step_x, tp._step_y, tp.dataset.shard_indices)
            np.testing.assert_allclose(float(mt["train/loss"]),
                                       float(mb["train/loss"]), rtol=1e-4)

    def test_vit_pipeline_parallel_matches_dense(self):
        from mercury_tpu.models import create_model
        from mercury_tpu.parallel.pipeline import (
            make_pp_apply, shard_stacked_blocks, stack_block_params)

        vit = create_model("vit", num_classes=10, num_layers=4,
                           d_model=32, num_heads=2,
                           compute_dtype="float32")
        x = jax.random.normal(jax.random.key(0), (4, 32, 32, 3))
        params = vit.init(jax.random.key(1), x, train=False)["params"]
        ref = vit.apply({"params": params}, x, train=False)

        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        stacked, rest = stack_block_params(params, 4)
        stacked = shard_stacked_blocks(stacked, mesh)
        pp = make_pp_apply(vit, mesh, num_microbatches=2)
        out = pp(stacked, rest, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_patchify_errors(self):
        from mercury_tpu.models import TransformerClassifier, create_model

        no_patch = TransformerClassifier(num_classes=10, d_model=32,
                                         num_heads=2, num_layers=1,
                                         max_len=64)
        x = jnp.zeros((2, 32, 32, 3))
        with pytest.raises(ValueError, match="patch_size"):
            no_patch.init(jax.random.key(0), x, train=False)
        bad = create_model("vit", num_classes=10, patch_size=5)
        with pytest.raises(ValueError, match="divisible"):
            bad.init(jax.random.key(0), x, train=False)
