"""Expert-parallelism tests: the all-to-all Switch dispatch
(``models/moe.py``) must reproduce the dense top-1 reference path exactly
when capacity admits every token, drop overflow tokens to zero when it
does not, and train end to end with experts sharded over the mesh.
Beyond-parity extension (SURVEY.md §2.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mercury_tpu.compat import shard_map

from mercury_tpu.models.moe import MoEMLP

pytestmark = pytest.mark.slow  # parallelism-matrix compile cost blows the tier-1 budget

B, T, D, E = 16, 8, 16, 8   # 8 experts over 4 devices → 2 experts/device


def ep_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("expert",))


@pytest.fixture(scope="module")
def setup():
    dense = MoEMLP(num_experts=E, d_model=D)
    x = jax.random.normal(jax.random.key(0), (B, T, D), jnp.float32)
    params = dense.init(jax.random.key(1), x)["params"]
    return dense, x, params


def ep_apply(params, x, mesh, capacity_factor, e=E):
    """Run the EP path inside shard_map: tokens sharded over 'expert' on
    batch, gate replicated, stacked expert params sharded on experts."""
    model = MoEMLP(num_experts=e, d_model=D, ep_axis="expert",
                   capacity_factor=capacity_factor)
    specs = {
        "gate": P(),
        "w_up": P("expert"), "b_up": P("expert"),
        "w_down": P("expert"), "b_down": P("expert"),
    }
    fn = shard_map(
        lambda p, x: model.apply({"params": p}, x),
        mesh=mesh,
        in_specs=({k: specs[k] for k in params}, P("expert")),
        out_specs=(P("expert"), P()),
    )
    return jax.jit(fn)(params, x)


class TestDensePath:
    def test_shapes_and_routing(self, setup):
        dense, x, params = setup
        y, aux = dense.apply({"params": params}, x)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert float(aux) > 0.0

    def test_bucketed_matches_onehot_oracle(self, setup):
        """The O(N) bucketed single-device path ≡ the O(E·N) one-hot
        oracle when capacity admits every token."""
        _, x, params = setup
        big = MoEMLP(num_experts=E, d_model=D, capacity_factor=float(E))
        y, aux = big.apply({"params": params}, x)
        ref, ref_aux = big.apply({"params": params}, x, method="reference")
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)


class TestExpertParallel:
    def test_matches_dense_when_capacity_suffices(self, setup):
        """capacity_factor=E → every token admitted → EP ≡ the one-hot
        oracle."""
        dense, x, params = setup
        ref, ref_aux = dense.apply({"params": params}, x, method="reference")
        y, aux = ep_apply(params, x, ep_mesh(), capacity_factor=float(E))
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)

    def test_overflow_tokens_drop_to_zero(self, setup):
        """Minimal capacity (1 slot/expert/device) → at most E tokens per
        device survive; every overflow token's output is exactly zero (the
        Switch semantics)."""
        dense, x, params = setup
        y, _ = ep_apply(params, x, ep_mesh(), capacity_factor=1e-6)
        rows = np.asarray(y).reshape(-1, D)
        zero_rows = int(np.sum(~np.any(rows != 0.0, axis=-1)))
        n_tokens, n_devices = rows.shape[0], 4
        # Each device keeps ≤ E tokens (1 per expert bucket).
        assert zero_rows >= n_tokens - n_devices * E
        assert zero_rows < n_tokens  # but the kept slots did compute

    def test_indivisible_experts_rejected(self, setup):
        dense, x, params = setup
        # 8 experts cannot split over 3 devices.
        with pytest.raises(ValueError, match="divisible"):
            ep_apply(params, x, ep_mesh(3), capacity_factor=2.0)

    def test_gradients_match_dense(self, setup):
        dense, x, params = setup
        mesh = ep_mesh()

        def loss_ep(p):
            y, aux = ep_apply(p, x, mesh, capacity_factor=float(E))
            return jnp.sum(y * y) + 0.01 * aux

        def loss_dense(p):
            y, aux = dense.apply({"params": p}, x, method="reference")
            return jnp.sum(y * y) + 0.01 * aux

        g_ep = jax.grad(loss_ep)(params)
        g_ref = jax.grad(loss_dense)(params)
        for a, b in zip(jax.tree_util.tree_leaves(g_ep),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestMoETransformer:
    """MoE as a first-class Transformer option: the block's MLP becomes a
    Switch MoE, aux losses are sowed, and expert parallelism composes with
    the full classifier."""

    kw = dict(num_classes=4, d_model=16, num_heads=2, num_layers=2,
              max_len=8, moe_experts=4)

    def _data(self):
        from mercury_tpu.models import TransformerClassifier

        x = jax.random.normal(jax.random.key(3), (8, 8, 6), jnp.float32)
        model = TransformerClassifier(**self.kw)
        params = model.init(jax.random.key(4), x, train=False)["params"]
        return model, x, params

    def test_dense_moe_forward_and_aux(self):
        model, x, params = self._data()
        logits, state = model.apply({"params": params}, x, train=False,
                                    mutable=["losses"])
        assert logits.shape == (8, 4)
        aux = jax.tree_util.tree_leaves(state["losses"])
        assert len(aux) == 2  # one sowed aux loss per block
        assert all(float(a) > 0 for a in aux)

    def test_ep_classifier_matches_dense(self):
        from mercury_tpu.models import TransformerClassifier

        model, x, params = self._data()
        # Same (generous) capacity on both sides: bucketing semantics match.
        dense_model = TransformerClassifier(moe_capacity_factor=8.0, **self.kw)
        ref, _ = dense_model.apply({"params": params}, x, train=False,
                                   mutable=["losses"])
        ep_model = TransformerClassifier(
            moe_ep_axis="expert", moe_capacity_factor=8.0, **self.kw)
        mesh = ep_mesh(2)   # 4 experts over 2 devices

        def spec_for(path, _):
            name = "/".join(str(getattr(p, "key", p)) for p in path)
            if "/moe/" in name and "gate" not in name:
                return P("expert")
            return P()

        specs = jax.tree_util.tree_map_with_path(spec_for, params)
        fn = shard_map(
            lambda p, x: ep_model.apply({"params": p}, x, train=False,
                                        mutable=["losses"])[0],
            mesh=mesh,
            in_specs=(specs, P("expert")),
            out_specs=P("expert"),
        )
        out = jax.jit(fn)(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_moe_transformer_trains_through_mercury_trainer(self):
        """config.moe_experts reaches the model, the sowed aux loss enters
        the objective (reported as train/moe_aux), and training learns."""
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="transformer", dataset="synthetic_seq", augmentation="none",
            world_size=8, batch_size=8, presample_batches=2, num_epochs=1,
            steps_per_epoch=10, eval_every=0, log_every=0,
            compute_dtype="float32", moe_experts=4, seed=0,
        )
        tr = Trainer(cfg, mesh=host_cpu_mesh(8))
        losses, auxes = [], []
        for _ in range(10):
            tr.state, m = tr.train_step(
                tr.state, tr.dataset.x_train, tr.dataset.y_train,
                tr.dataset.shard_indices,
            )
            losses.append(float(m["train/loss"]))
            auxes.append(float(m["train/moe_aux"]))
        assert all(np.isfinite(l) for l in losses)
        assert np.mean(losses[-3:]) < np.mean(losses[:3])
        assert all(a > 0 for a in auxes)  # router aux is live, not dropped

    def test_moe_requires_transformer(self):
        from mercury_tpu.config import TrainConfig
        from mercury_tpu.parallel.mesh import host_cpu_mesh
        from mercury_tpu.train.trainer import Trainer

        with pytest.raises(ValueError, match="moe_experts"):
            Trainer(TrainConfig(model="resnet18", dataset="synthetic",
                                moe_experts=4, world_size=8),
                    mesh=host_cpu_mesh(8))

    def test_pipeline_composes_with_ep_moe(self):
        """pp×EP (a round-2 rejection hole, now closed): a pipe×expert
        mesh stages the layers AND shards the experts — logits and router
        aux match the dense-path MoE through the same pipeline at ample
        capacity, in values and gradients."""
        from mercury_tpu.models import TransformerClassifier
        from mercury_tpu.parallel.pipeline import (
            make_pp_apply,
            shard_stacked_blocks,
            stack_block_params,
        )

        kw = {**self.kw, "moe_capacity_factor": 8.0}
        dense_model = TransformerClassifier(**kw)
        ep_model = TransformerClassifier(moe_ep_axis="expert", **kw)
        x = jax.random.normal(jax.random.key(3), (8, 8, 6), jnp.float32)
        params = dense_model.init(jax.random.key(5), x, train=False)["params"]
        stacked, rest = stack_block_params(params, self.kw["num_layers"])

        pipe_mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
        ref_fwd = make_pp_apply(dense_model, pipe_mesh, 2, with_aux=True)
        st_ref = shard_stacked_blocks(stacked, pipe_mesh, "pipe")
        ref_logits, _ = ref_fwd(st_ref, rest, x)
        # The router aux is a per-microbatch statistic, so it depends on
        # how the batch GROUPS into microbatches. EP rank e's microbatch t
        # holds samples e*(B/E) + [t*mb, (t+1)*mb); its aux psums over ep,
        # so the effective group is the UNION over ranks. Feed the dense
        # path the batch permuted into exactly those groups for an
        # apples-to-apples aux/grad reference (sum(lg^2) is
        # permutation-invariant, so the logits loss term is unaffected).
        group_perm = np.array([0, 1, 4, 5, 2, 3, 6, 7])
        ref_logits_g, ref_aux = ref_fwd(st_ref, rest, x[group_perm])

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("pipe", "expert"))
        ep_fwd = make_pp_apply(ep_model, mesh, 2, with_aux=True)
        st_ep = shard_stacked_blocks(stacked, mesh, "pipe",
                                     model=ep_model, ep="expert")
        ep_logits, ep_aux = ep_fwd(st_ep, rest, x)
        np.testing.assert_allclose(np.asarray(ep_logits),
                                   np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(ep_aux), float(ref_aux), rtol=1e-5)

        # Expert leaves physically shard over BOTH axes. (The stacked
        # tree has one block's structure with a leading layer axis.)
        moe_key = next(k for k in st_ep if "moe" in k.lower())
        wup = st_ep[moe_key]["w_up"]
        assert wup.addressable_shards[0].data.shape[0] == wup.shape[0] // 2
        assert wup.addressable_shards[0].data.shape[1] == wup.shape[1] // 2

        # Gradients: d(sum logits + aux)/d stacked match the dense path.
        def loss_ref(st):
            lg, ax = ref_fwd(st, rest, x[group_perm])
            return jnp.sum(lg * lg) + ax

        def loss_ep(st):
            lg, ax = ep_fwd(st, rest, x)
            return jnp.sum(lg * lg) + ax

        g_ref = jax.grad(loss_ref)(st_ref)
        g_ep = jax.grad(loss_ep)(st_ep)
        for a, b in zip(jax.tree_util.tree_leaves(g_ep),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_pipeline_ep_requires_mesh_axis(self):
        from mercury_tpu.models import TransformerClassifier
        from mercury_tpu.parallel.pipeline import make_pp_apply

        model = TransformerClassifier(moe_ep_axis="expert", **self.kw)
        mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
        with pytest.raises(ValueError, match="expert"):
            make_pp_apply(model, mesh, 2, with_aux=True)


class TestTraining:
    def test_ep_moe_learns(self, setup):
        """Regress a nonlinear target through the EP layer: loss falls and
        expert params stay sharded."""
        _, x, params = setup
        mesh = ep_mesh()
        target = jnp.tanh(x[..., ::-1] * 2.0)
        tx = optax.adam(3e-3)
        opt_state = tx.init(params)

        @jax.jit
        def step(p, opt_state):
            def loss_fn(p):
                y, aux = ep_apply(p, x, mesh, capacity_factor=4.0)
                return jnp.mean((y - target) ** 2) + 0.01 * aux

            loss, g = jax.value_and_grad(loss_fn)(p)
            updates, opt_state = tx.update(g, opt_state, p)
            return optax.apply_updates(p, updates), opt_state, loss

        p, losses = params, []
        for _ in range(25):
            p, opt_state, loss = step(p, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7
