"""graftlint Layer E: state-schema extraction, static gates
(GLE01–GLE06), golden parity, and the differential reshard
conformance half (GLE07–GLE10).

Three seeded-violation fixtures prove the gates bite: a state field
whose elastic policy is deleted (GLE01), a carried field whose carry
site is removed (GLE02), and an upgrade shim that no longer names the
field it drops (GLE03). The golden-parity tests pin the `--layer state`
CLI contract: HEAD verifies clean against the committed
``lint/state_schema.json``, a missing golden exits 2 with a regen hint,
a tampered golden diffs with a CI artifact, and --regen is
byte-stable. The differential test (slow) executes a real W=8→4→8
round-trip and asserts policy conformance.
"""

import json
import os

import pytest

from mercury_tpu.lint import golden
from mercury_tpu.lint import state as state_lint

# --------------------------------------------------------------------------
# fixtures: the real state-plane sources, plus seeded mutations of them
# --------------------------------------------------------------------------


def _real_source(key: str) -> str:
    root = os.path.dirname(os.path.dirname(state_lint.__file__))
    path = os.path.join(root, *state_lint.STATE_MODULES[key].split("/"))
    with open(path) as f:
        return f.read()


def _mutate(key: str, old: str, new: str) -> str:
    src = _real_source(key)
    assert old in src, f"fixture anchor {old!r} missing from {key}"
    return src.replace(old, new)


def field_without_policy_source() -> str:
    """Seeded GLE01: sel_counts loses its ELASTIC_POLICIES entry."""
    return _mutate("state", '    "sel_counts": "re-aggregate",\n', "")


def carry_site_removed_source() -> str:
    """Seeded GLE02: _carry_streamed_state computes the re-aggregated
    ledger but never assigns it into extra[...] — the carried field is
    silently discarded."""
    return _mutate("elastic", 'extra["sel_counts"] = jnp.asarray(',
                   "_dropped = jnp.asarray(")


def silent_drop_shim_source() -> str:
    """Seeded GLE03: the v2→v3 shim still works but no longer names the
    field it drops as a string constant — a restore path that drops
    state must say which field it drops."""
    return _mutate("checkpoint", 'field = "sel_counts"',
                   'field = "sel" + "_counts"')


# --------------------------------------------------------------------------
# extraction on HEAD
# --------------------------------------------------------------------------


class TestExtraction:
    def test_every_field_has_a_policy_in_vocabulary(self):
        facts = state_lint.extract_state_facts()
        assert facts["field_order"], "no MercuryState fields extracted"
        for name in facts["field_order"]:
            pol = facts["fields"][name]["policy"]
            assert pol in state_lint.POLICY_VOCAB, (name, pol)

    def test_known_policies_and_roles(self):
        facts = state_lint.extract_state_facts()
        f = facts["fields"]
        assert f["params"]["policy"] == "replicate"
        assert f["sel_counts"]["policy"] == "re-aggregate"
        assert f["sel_counts"]["dims"] == ["W", "L"]
        assert f["scoretable"]["policy"] == "reshard-exact"
        assert f["rng"]["policy"] == "re-seed"
        assert f["rng"]["role"] == "rng-key"
        assert f["pending_sel"]["policy"] == "drop-on-shrink"

    def test_carry_sites_extracted(self):
        facts = state_lint.extract_state_facts()
        carry = facts["carry"]
        assert "rng" in carry["replace_kwargs"]
        assert any("fold_in" in e for e in carry["replace_kwargs"]["rng"])
        assert "sel_counts" in carry["carry_extra"]
        assert carry["extra_splat"]
        assert carry["reprime"]["pending_sel"]

    def test_lineage_and_shims_extracted(self):
        facts = state_lint.extract_state_facts()
        lineage = facts["lineage"]
        assert lineage["head"] == lineage["versions"][-1]
        for old, new in zip(lineage["versions"], lineage["versions"][1:]):
            assert f"{old}->{new}" in facts["shims"]["pairs"]
        assert facts["shims"]["unknown_field_raise"]

    def test_manifest_parity_extracted(self):
        facts = state_lint.extract_state_facts()
        assert "state_schema_sha" in facts["manifest"]["keys"]
        assert facts["manifest"]["restore_checks_sha"]
        assert "state_schema_sha" in facts["manifest"][
            "reshard_begin_detail"]

    def test_head_extraction_has_no_findings(self):
        facts = state_lint.extract_state_facts()
        assert state_lint.check_extraction(facts) == []


class TestSeededFixtures:
    """Each planted state-contract bug must be caught by rule id."""

    def test_field_without_policy_caught(self):
        facts = state_lint.extract_state_facts(
            sources={"state": field_without_policy_source()})
        errors = state_lint.check_extraction(facts)
        assert any("GLE01" in e and "sel_counts" in e
                   for e in errors), errors

    def test_carry_site_removed_caught(self):
        facts = state_lint.extract_state_facts(
            sources={"elastic": carry_site_removed_source()})
        errors = state_lint.check_extraction(facts)
        assert any("GLE02" in e and "sel_counts" in e
                   for e in errors), errors

    def test_silent_drop_shim_caught(self):
        facts = state_lint.extract_state_facts(
            sources={"checkpoint": silent_drop_shim_source()})
        errors = state_lint.check_extraction(facts)
        assert any("GLE03" in e and "sel_counts" in e
                   for e in errors), errors

    def test_rng_policy_change_caught(self):
        # GLE05: declaring rng as anything but re-seed is a key-reuse
        # hazard even when a carry site exists.
        facts = state_lint.extract_state_facts(
            sources={"state": _mutate("state", '"rng": "re-seed"',
                                      '"rng": "replicate"')})
        errors = state_lint.check_extraction(facts)
        assert any("GLE05" in e and "rng" in e for e in errors), errors

    def test_unstamped_manifest_caught(self):
        # GLE06: removing the manifest stamp breaks drift detection.
        facts = state_lint.extract_state_facts(
            sources={"checkpoint": _mutate(
                "checkpoint", '"state_schema_sha": state_schema_sha(),',
                "")})
        errors = state_lint.check_extraction(facts)
        assert any("GLE06" in e for e in errors), errors


# --------------------------------------------------------------------------
# golden parity (--layer state contract)
# --------------------------------------------------------------------------


class TestGoldenParity:
    def test_head_verifies_against_committed_golden(self):
        errors, warnings = state_lint.run_state_check()
        assert errors == [], "\n".join(errors + warnings)

    def test_missing_golden_raises_file_not_found(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            state_lint.run_state_check(
                state_schema_path=str(tmp_path / "missing.json"))

    def test_tampered_golden_diffs_and_writes_artifact(self, tmp_path):
        doc = golden.load_golden(state_lint.default_state_schema_path(),
                                 state_lint.STATE_SCHEMA,
                                 state_lint.REGEN_HINT)
        doc["facts"]["fields"]["ema"]["policy"] = "replicate"
        tampered = tmp_path / "state_schema.json"
        tampered.write_text(json.dumps(doc))
        out = tmp_path / "diff.txt"
        errors, _ = state_lint.run_state_check(
            state_schema_path=str(tampered), diff_out=str(out))
        assert any("drifted" in e for e in errors)
        assert "facts.fields" in out.read_text()

    def test_regen_writes_byte_stable_golden(self, tmp_path):
        p = tmp_path / "state_schema.json"
        state_lint.run_state_check(state_schema_path=str(p), regen=True)
        first = p.read_text()
        state_lint.run_state_check(state_schema_path=str(p), regen=True)
        assert p.read_text() == first
        assert json.loads(first)["schema"] == state_lint.STATE_SCHEMA

    def test_committed_sha_matches_checkpoint_module_view(self):
        # checkpoint.state_schema_sha() reads the committed golden; the
        # manifest stamp must equal a fresh extraction's digest.
        from mercury_tpu.train import checkpoint as ckpt

        facts = state_lint.extract_state_facts()
        assert (ckpt.state_schema_sha()
                == state_lint.schema_sha_of_facts(facts))

    def test_sha_ignores_carry_evidence_churn(self):
        # The stamp digests fields + lineage only — provenance or carry
        # evidence drift must not invalidate every manifest.
        facts = state_lint.extract_state_facts()
        sha = state_lint.schema_sha_of_facts(facts)
        facts2 = json.loads(json.dumps(facts))
        facts2["carry"]["replace_kwargs"]["rng"] = ["something.else"]
        assert state_lint.schema_sha_of_facts(facts2) == sha
        facts3 = json.loads(json.dumps(facts))
        facts3["lineage"]["head"] = "v99"
        assert state_lint.schema_sha_of_facts(facts3) != sha

    def test_cli_exit_codes(self, tmp_path, capsys):
        assert state_lint.main([]) == 0
        assert "GLE01-GLE06" not in capsys.readouterr().err
        missing = str(tmp_path / "nope.json")
        assert state_lint.main(["--state-schema", missing]) == 2
        assert "--regen" in capsys.readouterr().err

    def test_cli_never_imports_jax(self):
        # The static half must run on the jax-free CI lint job.
        import subprocess
        import sys
        code = ("import sys; sys.modules['jax'] = None\n"
                "from mercury_tpu.lint import state\n"
                "sys.exit(state.main([]))\n")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              cwd=os.path.dirname(os.path.dirname(
                                  os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr


# --------------------------------------------------------------------------
# differential reshard conformance (GLE07–GLE10, runtime half)
# --------------------------------------------------------------------------


@pytest.mark.slow
class TestDifferential:
    def test_round_trip_is_conformant(self):
        findings = state_lint.run_differential(plans=("scoretable",),
                                               steps=2)
        assert findings == [], "\n".join(findings)
