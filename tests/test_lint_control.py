"""graftlint Layer S: control-plane extraction, model checking, golden
parity, and journal-conformance replay.

Three seeded-violation fixtures prove the gates bite: a level-skipping
degrade (GLS10), an unjournaled restart path (GLS11), and a latch-free
supervisor whose machine oscillates (GLS03). The conformance half is
exercised both on synthetic journals (each invariant violated on
purpose) and on a real HostSupervisor episode recorded through a real
EventJournal — which must replay with zero findings against the
committed ``lint/control_plane.json``.
"""

import json
import os

import pytest

from mercury_tpu.lint import control, golden, modelcheck
from mercury_tpu.obs.events import EventJournal, load_events
from mercury_tpu.runtime.supervisor import (
    BUDGET_BUCKETS,
    LEVEL_NAMES,
    HostSupervisor,
)

# --------------------------------------------------------------------------
# fixtures: the real supervisor source, plus seeded mutations of it
# --------------------------------------------------------------------------


def _real_supervisor_source() -> str:
    root = os.path.dirname(control.__file__)
    path = os.path.join(os.path.dirname(root),
                        *control.CONTROL_MODULES["supervisor"].split("/"))
    with open(path) as f:
        return f.read()


def _mutate_method(src: str, method: str, old: str, new: str) -> str:
    """Apply a textual replacement confined to one method body."""
    start = src.index(f"def {method}")
    end = src.index("\n    def ", start + 1)
    body = src[start:end]
    assert old in body, f"fixture anchor {old!r} missing from {method}"
    return src[:start] + body.replace(old, new) + src[end:]


def level_skip_source() -> str:
    """Seeded violation: _degrade jumps TWO levels per decision."""
    return _mutate_method(_real_supervisor_source(), "_degrade",
                          "self._level = src + 1",
                          "self._level = src + 2")


def unjournaled_restart_source() -> str:
    """Seeded violation: _try_restart journals nothing (both the
    success and the failure emit are renamed off the journal API)."""
    return _mutate_method(_real_supervisor_source(), "_try_restart",
                          "self._journal_emit(",
                          "self._offline_note(")


def latch_free_source() -> str:
    """Seeded violation: SLO breaches no longer latch and the probe is
    never pinned — the machine can recover and re-breach forever with
    no release edge (the oscillation GLS03 forbids)."""
    src = _mutate_method(_real_supervisor_source(), "_check_slos",
                         "slo.breached = status is not None",
                         "pass  # latch removed")
    return _mutate_method(src, "_maybe_probe",
                          "slo_pinned = any(s.breached "
                          "for s in self._slos)",
                          "slo_pinned = False")


def _machine():
    return control.build_machine(control.extract_control_facts())


def ev(kind, eid, parent=None, host=0, step=0, **detail):
    return {"kind": kind, "event_id": eid, "parent_id": parent,
            "host": host, "step": step, "detail": detail}


# --------------------------------------------------------------------------
# extraction on HEAD
# --------------------------------------------------------------------------


class TestExtraction:
    def test_facts_match_runtime_constants(self):
        facts = control.extract_control_facts()
        assert facts["levels"] == list(LEVEL_NAMES)
        assert facts["buckets"] == list(BUDGET_BUCKETS)

    def test_ladder_moves_one_level_with_guards(self):
        facts = control.extract_control_facts()
        assert facts["degrade"]["delta"] == 1
        assert facts["recover"]["delta"] == -1
        assert facts["degrade"]["absorbing_guard"]
        assert facts["recover"]["floor_guard"]
        assert facts["recover"]["budget_reset_on_full_recovery"]

    def test_every_transition_site_journals(self):
        facts = control.extract_control_facts()
        for site, kinds in facts["transition_sites"].items():
            assert kinds, f"{site} journals nothing"
        assert "supervisor/degrade" in facts["degrade"]["emits"]
        assert "supervisor/recover" in facts["recover"]["emits"]

    def test_slo_latch_and_probe_pin_extracted(self):
        facts = control.extract_control_facts()
        assert facts["slo"]["latched"]
        assert facts["slo"]["breach_degrades"]
        assert facts["probe"]["pinned_by_latched_slo"]
        assert facts["probe"]["ok_recovers"]
        assert facts["exhaustion"]["once_latched"]
        assert facts["restart"]["consumes_budget_on_attempt"]

    def test_head_extraction_has_no_findings(self):
        facts = control.extract_control_facts()
        assert control.check_extraction(facts) == []

    def test_fault_kinds_and_triggers_populate_alphabet(self):
        facts = control.extract_control_facts()
        assert "scorer_die" in facts["fault_kinds"]
        assert facts["anomaly_triggers"]


class TestSeededFixtures:
    """Each planted control-plane bug must be caught by name."""

    def test_level_skipping_degrade_caught(self):
        facts = control.extract_control_facts(
            sources={"supervisor": level_skip_source()})
        errors = control.check_extraction(facts)
        assert any("GLS10" in e and "_degrade" in e for e in errors), errors

    def test_unjournaled_restart_caught(self):
        facts = control.extract_control_facts(
            sources={"supervisor": unjournaled_restart_source()})
        errors = control.check_extraction(facts)
        assert any("GLS11" in e and "_try_restart" in e
                   for e in errors), errors

    def test_latch_free_oscillation_caught_by_model_checker(self):
        facts = control.extract_control_facts(
            sources={"supervisor": latch_free_source()})
        machine = control.build_machine(facts)
        errors = modelcheck.check_invariants(machine)
        assert any("GLS03" in e for e in errors), errors


# --------------------------------------------------------------------------
# machine construction + invariants on HEAD
# --------------------------------------------------------------------------


class TestMachine:
    def test_machine_well_formed(self):
        m = _machine()
        ids = {s["id"] for s in m["states"]}
        assert m["initial"] in ids
        assert m["states"] and m["edges"]
        for e in m["edges"]:
            assert e["from"] in ids and e["to"] in ids

    def test_state_space_is_the_full_reachable_product(self):
        m = _machine()
        # level 0 never latches a pin-free probe state with a latch set?
        # No: breaches latch at any level, so latched level-0 states exist.
        levels = {s["level"] for s in m["states"]}
        assert levels == set(range(len(LEVEL_NAMES)))
        assert {s["bucket"] for s in m["states"]} <= set(BUDGET_BUCKETS)

    def test_invariants_hold_on_head(self):
        assert modelcheck.check_invariants(_machine()) == []

    def test_every_edge_emit_is_registered(self):
        m = _machine()
        modeled = set(m["kind_rules"])
        for e in m["edges"]:
            for k in e["emits"]:
                assert k in modeled

    def test_absorbing_top_emits_nothing_on_further_degrade(self):
        # _degrade's guard returns before journaling at the top level:
        # breach/exhaustion edges from uniform emit only their own kind.
        m = _machine()
        top = len(LEVEL_NAMES) - 1
        lv = {s["id"]: s["level"] for s in m["states"]}
        for e in m["edges"]:
            if lv[e["from"]] == top:
                assert "supervisor/degrade" not in e["emits"], e


# --------------------------------------------------------------------------
# golden parity (--layer control contract)
# --------------------------------------------------------------------------


class TestGoldenParity:
    def test_head_verifies_against_committed_golden(self):
        errors, warnings = control.run_control_check()
        assert errors == [], "\n".join(errors + warnings)

    def test_missing_golden_raises_file_not_found(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            control.run_control_check(
                control_path=str(tmp_path / "missing.json"))

    def test_tampered_golden_diffs_and_writes_artifact(self, tmp_path):
        doc = golden.load_golden(control.default_control_path(),
                                 control.CONTROL_SCHEMA,
                                 control.REGEN_HINT)
        doc["facts"]["levels"] = ["async", "uniform"]
        tampered = tmp_path / "control_plane.json"
        tampered.write_text(json.dumps(doc))
        out = tmp_path / "diff.txt"
        errors, _ = control.run_control_check(
            control_path=str(tampered), diff_out=str(out))
        assert any("drifted" in e for e in errors)
        assert "facts.levels" in out.read_text()

    def test_regen_writes_byte_stable_golden(self, tmp_path):
        p = tmp_path / "control_plane.json"
        control.run_control_check(control_path=str(p), regen=True)
        first = p.read_text()
        control.run_control_check(control_path=str(p), regen=True)
        assert p.read_text() == first
        assert json.loads(first)["schema"] == control.CONTROL_SCHEMA

    def test_all_or_nothing_across_six_goldens(self, tmp_path):
        """Satellite: a partial failure across the whole golden set must
        rewrite nothing — stage all six, fail the last, diff none."""
        paths = [tmp_path / f"g{i}.json" for i in range(6)]
        for i, p in enumerate(paths):
            p.write_text(json.dumps({"old": i}))
        writes = [(str(p), {"new": i}) for i, p in enumerate(paths[:-1])]
        writes.append((str(paths[-1]), {"bad": object()}))
        with pytest.raises(TypeError):
            golden.commit_goldens(writes)
        for i, p in enumerate(paths):
            assert json.loads(p.read_text()) == {"old": i}
        assert list(tmp_path.glob("*.tmp")) == []

    def test_regen_all_goldens_includes_control_plane(self, tmp_path,
                                                      monkeypatch):
        """The one-stop --regen commits control_plane.json AND
        state_schema.json in the same transaction as the other goldens
        (layer measurement stubbed — the tracing layers have their own
        tests)."""
        from mercury_tpu.lint import audit, concurrency, perf, sharding
        from mercury_tpu.lint import state as state_lint

        monkeypatch.setattr(audit, "PLAN_NAMES", ())
        monkeypatch.setattr(audit, "ensure_cpu_devices", lambda: None)
        monkeypatch.setattr(sharding, "check_axis_registry", lambda: [])
        monkeypatch.setattr(concurrency, "extract_manifest",
                            lambda paths: {"schema": "stub"})
        monkeypatch.setattr(audit, "budgets_doc", lambda ms: {"s": 1})
        monkeypatch.setattr(sharding, "shard_budgets_doc",
                            lambda ms: {"s": 1})
        monkeypatch.setattr(perf, "perf_budgets_doc",
                            lambda ms, rs: {"s": 1})
        ctrl = tmp_path / "control_plane.json"
        schema = tmp_path / "state_schema.json"
        errors, warnings = golden.regen_all_goldens(
            budgets_path=str(tmp_path / "budgets.json"),
            shard_budgets_path=str(tmp_path / "shard.json"),
            manifest_path=str(tmp_path / "threads.json"),
            perf_budgets_path=str(tmp_path / "perf.json"),
            control_path=str(ctrl),
            state_schema_path=str(schema))
        assert errors == []
        doc = json.loads(ctrl.read_text())
        assert doc["schema"] == control.CONTROL_SCHEMA
        assert any("control_plane.json" in w for w in warnings)
        sdoc = json.loads(schema.read_text())
        assert sdoc["schema"] == state_lint.STATE_SCHEMA
        assert any("state_schema.json" in w for w in warnings)


# --------------------------------------------------------------------------
# journal conformance replay: synthetic journals
# --------------------------------------------------------------------------


class TestConformanceSynthetic:
    def test_clean_episode_replays_conformant(self):
        events = [
            ev("supervisor/slo_breach", "e1", slo="scorer_service",
               status="stale"),
            ev("supervisor/degrade", "e2", parent="e1",
               **{"from": "async", "to": "sync"}),
            ev("supervisor/slo_release", "e3", parent="e1",
               slo="scorer_service"),
            ev("supervisor/probe_ok", "e4", parent="e2", level=1),
            ev("supervisor/recover", "e5", parent="e4",
               **{"from": "sync", "to": "async"}),
        ]
        assert control.check_journal_conformance(events, _machine()) == []

    def test_level_skipping_degrade_flagged(self):
        events = [ev("supervisor/degrade", "e1",
                     **{"from": "async", "to": "frozen"})]
        findings = control.check_journal_conformance(events, _machine())
        assert any("skips levels" in f for f in findings)

    def test_recover_while_slo_latched_flagged(self):
        """The oscillation guard: a recover with a breach still latched
        (no release in between) is exactly what GLS03 forbids."""
        events = [
            ev("supervisor/slo_breach", "e1", slo="x", status="bad"),
            ev("supervisor/degrade", "e2", parent="e1",
               **{"from": "async", "to": "sync"}),
            ev("supervisor/probe_ok", "e3", parent="e2", level=1),
            ev("supervisor/recover", "e4", parent="e3",
               **{"from": "sync", "to": "async"}),
        ]
        findings = control.check_journal_conformance(events, _machine())
        assert any("latched" in f for f in findings)

    def test_unjournaled_transition_flagged(self):
        events = [
            ev("supervisor/degrade", "e1",
               **{"from": "async", "to": "sync"}),
            ev("supervisor/degrade", "e2",
               **{"from": "frozen", "to": "uniform"}),
        ]
        findings = control.check_journal_conformance(events, _machine())
        assert any("was not journaled" in f for f in findings)

    def test_rebreach_without_release_flagged(self):
        events = [
            ev("supervisor/slo_breach", "e1", slo="x", status="bad"),
            ev("supervisor/slo_breach", "e2", slo="x", status="bad"),
        ]
        findings = control.check_journal_conformance(events, _machine())
        assert any("re-breach" in f for f in findings)

    def test_restart_after_exhaustion_flagged(self):
        events = [
            ev("supervisor/restart_failed", "e1", unit="s", attempt=1,
               budget=1),
            ev("supervisor/exhausted", "e2", parent="e1", unit="s",
               budget=1),
            ev("supervisor/restart", "e3", unit="s", attempt=2,
               budget=1),
        ]
        findings = control.check_journal_conformance(events, _machine())
        assert any("after exhaustion" in f for f in findings)

    def test_bad_parent_chain_flagged(self):
        events = [
            ev("supervisor/restart", "e1", unit="s", attempt=1, budget=3),
            ev("supervisor/exhausted", "e2", parent="e1", unit="s",
               budget=3),
        ]
        findings = control.check_journal_conformance(events, _machine())
        assert any("parented to" in f for f in findings)

    def test_hosts_replay_independently(self):
        events = [
            ev("supervisor/degrade", "a1", host=0,
               **{"from": "async", "to": "sync"}),
            ev("supervisor/degrade", "b1", host=1,
               **{"from": "async", "to": "sync"}),
        ]
        assert control.check_journal_conformance(events, _machine()) == []

    def test_ambient_kinds_pass_through(self):
        events = [
            ev("fault/fired", "e1", kind_name="scorer_die"),
            ev("anomaly/triggered", "e2", trigger="is_losing"),
        ]
        assert control.check_journal_conformance(events, _machine()) == []

    def test_coverage_names_unobserved_transitions(self):
        events = [ev("supervisor/degrade", "e1",
                     **{"from": "async", "to": "sync"})]
        gaps = control.conformance_coverage(events, _machine())
        assert any("supervisor/recover" in g for g in gaps)
        assert any("never observed from level" in g for g in gaps)


# --------------------------------------------------------------------------
# journal conformance: rotation / torn shards (satellite d)
# --------------------------------------------------------------------------


class TestConformanceRotation:
    FULL = [
        ev("supervisor/slo_breach", "e1", slo="x", status="bad"),
        ev("supervisor/degrade", "e2", parent="e1",
           **{"from": "async", "to": "sync"}),
        ev("supervisor/slo_release", "e3", parent="e1", slo="x"),
        ev("supervisor/probe_ok", "e4", parent="e2", level=1),
        ev("supervisor/recover", "e5", parent="e4",
           **{"from": "sync", "to": "async"}),
    ]

    def test_every_rotation_suffix_replays_clean(self):
        """A rotated shard is a suffix of a valid run: state binds from
        the first event that declares it, so no suffix may produce a
        false violation."""
        m = _machine()
        for start in range(len(self.FULL)):
            findings = control.check_journal_conformance(
                self.FULL[start:], m)
            assert findings == [], (start, findings)

    def test_torn_final_line_replays_clean(self, tmp_path):
        j = EventJournal(str(tmp_path), host=0)
        j.emit("supervisor/slo_breach", 1,
               detail={"slo": "x", "status": "bad"})
        j.emit("supervisor/degrade", 1,
               detail={"from": "async", "to": "sync"})
        j.close()
        shard = tmp_path / "events.h0.jsonl"
        with open(shard, "a") as f:
            f.write('{"schema": "torn mid-wri')  # crash mid-append
        events = load_events(str(tmp_path))
        assert len(events) == 2
        assert control.check_journal_conformance(events, _machine()) == []


# --------------------------------------------------------------------------
# end to end: a real supervisor episode through a real journal
# --------------------------------------------------------------------------


class TestConformanceIntegration:
    def test_real_episode_replays_conformant(self, tmp_path):
        """Drive a real HostSupervisor through breach -> degrade ->
        release -> probe -> recover with journaling on; the recorded
        shard must replay conformant against the committed machine."""
        journal = EventJournal(str(tmp_path), host=0)
        sup = HostSupervisor(restart_budget=3, backoff_s=0.0,
                             probe_every=1, poll_s=0.0, journal=journal)
        breaching = [True]
        sup.register_slo("scorer_service",
                         lambda: "stale" if breaching[0] else None)
        sup.set_ladder(probe=lambda: None, revive=lambda: None)

        sup.tick(1)                    # rising edge: breach + degrade
        assert sup.level() == 1
        sup.tick(2)                    # latched: probe pinned, no climb
        assert sup.level() == 1
        breaching[0] = False
        sup.tick(3)                    # falling edge: release
        sup.tick(4)                    # probe_ok -> recover
        assert sup.level() == 0
        journal.close()

        events = load_events(str(tmp_path))
        kinds = [e["kind"] for e in events]
        assert "supervisor/slo_breach" in kinds
        assert "supervisor/recover" in kinds
        assert control.check_journal_conformance(events) == []

    def test_exhaustion_episode_replays_conformant(self, tmp_path):
        """Budget-0 exhaustion (the chaos smoke's config): the unit
        exhausts with zero attempts and the ladder escalates."""
        journal = EventJournal(str(tmp_path), host=0)
        sup = HostSupervisor(restart_budget=0, backoff_s=0.0,
                             probe_every=0, poll_s=0.0, journal=journal)
        sup.register_unit("scorer", alive=lambda: False,
                          restart=lambda: None, escalates=True)
        sup.tick(1)
        assert sup.level() == 1
        journal.close()
        events = load_events(str(tmp_path))
        assert "supervisor/exhausted" in [e["kind"] for e in events]
        assert control.check_journal_conformance(events) == []

    def test_cli_replay_and_empty_dir(self, tmp_path, capsys):
        run = tmp_path / "run"
        run.mkdir()
        journal = EventJournal(str(run), host=0)
        journal.emit("supervisor/degrade", 1,
                     detail={"from": "async", "to": "sync"})
        journal.close()
        assert control.main([str(run), "--coverage"]) == 0
        out = capsys.readouterr().out
        assert "replay conformant" in out
        assert "warning: coverage:" in out
        empty = tmp_path / "empty"
        empty.mkdir()
        assert control.main([str(empty)]) == 2


# --------------------------------------------------------------------------
# supervisor model-state surface (satellite c)
# --------------------------------------------------------------------------


class TestModelStateSurface:
    def _machine_ids(self):
        return {s["id"] for s in _machine()["states"]}

    def test_initial_state_id_is_machine_initial(self):
        sup = HostSupervisor(restart_budget=3, backoff_s=0.0,
                             probe_every=0, poll_s=0.0)
        ms = sup.model_state()
        assert ms["state_id"] == _machine()["initial"]

    def test_live_state_ids_stay_inside_machine(self):
        sup = HostSupervisor(restart_budget=1, backoff_s=0.0,
                             probe_every=0, poll_s=0.0)
        breaching = [False]
        sup.register_slo("scorer_service",
                         lambda: "bad" if breaching[0] else None)
        sup.register_unit("scorer", alive=lambda: False,
                          restart=lambda: (_ for _ in ()).throw(
                               RuntimeError("down")),
                          escalates=True)
        ids = self._machine_ids()
        assert sup.model_state()["state_id"] in ids
        breaching[0] = True
        for step in range(1, 5):
            sup.tick(step)
            ms = sup.model_state()
            assert ms["state_id"] in ids, ms
        assert sup.model_state()["probe_pinned"] is True
        assert sup.model_state()["latched_slos"] == ["scorer_service"]

    def test_stats_and_summary_expose_model_state(self):
        sup = HostSupervisor(restart_budget=3, backoff_s=0.0,
                             probe_every=0, poll_s=0.0)
        stats = sup.stats()
        assert stats["supervisor/slo_latched"] == 0.0
        assert stats["supervisor/probe_pinned"] == 0.0
        summary = sup.summary()
        assert summary["model_state"]["state_id"] == _machine()["initial"]
        assert summary["model_state"]["budget_bucket"] == BUDGET_BUCKETS[0]
